"""Host-side span tracing: a lock-cheap ring buffer + Perfetto export.

``metrics.py`` (PR 5) answers "how much / how often" with counters and
histograms; this module answers "where did this request's 100 ms go?"
with a TIMELINE. It is the host-side half of the observability story —
the device half stays ``jax.profiler`` / XProf named scopes — and the
observation layer the ROADMAP item 4 controller reads: overlap problems
(cold-tier prefetch behind compute, coalesce wait vs dispatch) are
invisible in percentiles but obvious in a trace.

Design constraints, in order:

1. **Zero cost when off.** Tracing is opt-in (``QT_TRACE=1`` /
   ``QT_TRACE=/path/out.json`` / :func:`enable`); disabled, every hook
   is one attribute check (``record``) or a shared no-op context
   manager (``span``) — the instrumented hot paths (the serving
   coalescer, the pipeline worker) reuse timestamps they already take
   for ``stats()``, so no extra clock reads either.
2. **Lock-cheap when on.** Records land in a fixed-capacity ring
   buffer: one atomic ``next(itertools.count())`` for the slot, one
   list-item store for the record (both single bytecode effects under
   the GIL — no lock, no allocation beyond the record tuple). When the
   ring wraps, the oldest spans are overwritten: a long-running server
   keeps the RECENT window, bounded memory by construction
   (``scripts/check_leak.py`` phase 7 pins this).
3. **Never inside jit.** Spans time HOST work around device dispatches;
   nothing here touches a traced program, so the PR 5 invariants (zero
   per-step host syncs, bit-identical outputs with tracing on/off,
   donation intact) hold trivially — and are still pinned explicitly in
   ``tests/test_serving.py``.

A span record is ``(name, tid, t0, dur, trace_id, args)``: ``t0``/
``dur`` in ``time.perf_counter()`` seconds, ``tid`` the recording
thread, ``trace_id`` an optional correlation id (the serving layer
gives every request one and stamps each request span with the id of
the BATCH that carried it, so a request's admission -> coalesce ->
dispatch -> scatter path is one click-through in the viewer), ``args``
a small JSON-able dict.

:func:`export_chrome_trace` writes the Chrome trace-event JSON the
Perfetto UI (https://ui.perfetto.dev) and ``chrome://tracing`` load
directly: complete (``"ph": "X"``) events on named thread tracks, span
``args`` (including ``trace_id``) visible in the selection panel.

**Cross-process propagation** (the fleet plane's tracing leg): a span
timeline is per-process, but a REQUEST crosses processes — a client
submits, a serve replica answers. :func:`inject` stamps a compact
trace context (``trace_id``, optional parent span name, the sender's
replica label) into any dict-shaped request metadata; the receiving
side calls :func:`extract` and continues recording under the SAME
``trace_id`` (``serving.MicroBatchServer.submit(node_id, context=...)``
does this). Injected ids are *globally* unique — the pid rides the
high bits (:meth:`Tracer.new_global_trace_id`) so ids minted by
different clients/replicas never collide in a merged trace. Each
process exports with its own real ``pid`` plus a ``process_name``
metadata row (the replica label, ``QT_REPLICA`` / :func:`set_replica`
/ the ``replica=`` export arg), and :func:`merge_chrome_traces`
concatenates N exports into one file — Perfetto renders one process
track group per replica, and searching the injected ``trace_id``
lights up the request's spans across every process that touched it.

Usage::

    from quiver_tpu import tracing
    tracing.enable()
    with tracing.span("stage.load", args={"rows": 4096}):
        ...
    tracing.export_chrome_trace("/tmp/trace.json")   # -> Perfetto

    # client process:
    meta = tracing.inject({})                  # -> request metadata
    # replica process (its spans carry meta's trace_id):
    ctx = tracing.extract(meta)
    with tracing.span("serve.request", trace_id=ctx.trace_id):
        ...
"""

from __future__ import annotations

import atexit
import itertools
import json
import os
import threading
import time
from typing import Dict, List, NamedTuple, Optional, Sequence, Tuple

Record = Tuple[str, int, float, float, Optional[int], Optional[dict]]

DEFAULT_CAPACITY = 65536

# the compact carrier keys inject()/extract() use inside request
# metadata — namespaced so they coexist with application fields
CTX_TRACE_ID = "qt.trace_id"
CTX_PARENT = "qt.parent"
CTX_REPLICA = "qt.replica"


class TraceContext(NamedTuple):
    """The propagated trace context: the correlation id a client
    minted, the span name it was under (informational), and the
    SENDER's replica label."""

    trace_id: int
    parent: Optional[str] = None
    replica: Optional[str] = None


# the process's replica label (fleet identity): QT_REPLICA env, or
# set_replica(); stamps outgoing contexts and the Perfetto export's
# process_name row
_replica: Optional[str] = os.environ.get("QT_REPLICA") or None


def set_replica(name: Optional[str]) -> None:
    """Set this process's replica label (overrides ``QT_REPLICA``)."""
    global _replica
    _replica = str(name) if name else None


def get_replica() -> Optional[str]:
    return _replica


class _NullSpan:
    """The shared do-nothing context manager handed out while tracing
    is disabled — no per-call allocation on the disabled path."""

    __slots__ = ()

    def __enter__(self) -> "_NullSpan":
        return self

    def __exit__(self, *exc) -> None:
        pass


_NULL_SPAN = _NullSpan()


class _Span:
    __slots__ = ("_tracer", "name", "trace_id", "args", "t0")

    def __init__(self, tracer: "Tracer", name: str,
                 trace_id: Optional[int], args: Optional[dict]):
        self._tracer = tracer
        self.name = name
        self.trace_id = trace_id
        self.args = args

    def __enter__(self) -> "_Span":
        self.t0 = time.perf_counter()
        return self

    def __exit__(self, *exc) -> None:
        self._tracer.record(self.name, self.t0,
                            time.perf_counter() - self.t0,
                            self.trace_id, self.args)


class Tracer:
    """Fixed-capacity span ring buffer (see module doc for the
    concurrency argument). One process-wide instance normally suffices
    (:func:`get_tracer`); independent tracers compose for tests."""

    def __init__(self, capacity: int = DEFAULT_CAPACITY):
        if capacity < 1:
            raise ValueError(f"capacity must be >= 1, got {capacity}")
        self.capacity = int(capacity)
        self._ring: List[Optional[Record]] = [None] * self.capacity
        self._seq = itertools.count()
        self._ids = itertools.count(1)
        self._tid_names: Dict[int, str] = {}
        self._enabled = False
        # optional tail sampler (quiver_tpu.tailsampling.TailSampler):
        # every recorded span is ALSO offered to it — the always-on
        # keep/drop decision rides the same one recording path, one
        # attribute check when absent
        self._sampler = None

    # -- switch -------------------------------------------------------------
    @property
    def enabled(self) -> bool:
        return self._enabled

    def enable(self, capacity: Optional[int] = None) -> "Tracer":
        """Turn recording on (optionally resizing — a resize discards
        already-recorded spans)."""
        if capacity is not None and int(capacity) != self.capacity:
            if capacity < 1:
                raise ValueError(f"capacity must be >= 1, got {capacity}")
            self.capacity = int(capacity)
            self.clear()
        self._enabled = True
        return self

    def disable(self) -> "Tracer":
        self._enabled = False
        return self

    def clear(self) -> None:
        """Drop every recorded span (the ring survives, emptied)."""
        # swap ring and sequence together; record() indexes a LOCAL ref
        # of the ring by its own length, so a racing writer lands its
        # record in whichever ring it grabbed, never out of bounds. A
        # racing writer may register its thread name into the old dict
        # (lost) — its spans still export, just without the name row.
        self._ring = [None] * self.capacity
        self._seq = itertools.count()
        self._tid_names = {}

    # -- recording ----------------------------------------------------------
    def new_trace_id(self) -> int:
        """A fresh correlation id (process-unique, monotonic)."""
        return next(self._ids)

    def new_global_trace_id(self) -> int:
        """A fresh correlation id safe to PROPAGATE across processes:
        the pid rides the high bits above the local counter, so two
        replicas (or a client and a replica) can each mint ids and a
        merged fleet trace still has no collisions. Same int domain as
        :meth:`new_trace_id` — span records don't care which minted
        theirs."""
        return ((os.getpid() & 0x3FFFFF) << 24) | \
            (next(self._ids) & 0xFFFFFF)

    def record(self, name: str, t0: float, dur: float,
               trace_id: Optional[int] = None,
               args: Optional[dict] = None) -> None:
        """File one completed span from timestamps the caller already
        holds (``t0`` from ``time.perf_counter()``, ``dur`` seconds) —
        the zero-extra-clock-read form the hot paths use."""
        if not self._enabled:
            return
        tid = threading.get_ident()
        if tid not in self._tid_names:
            self._tid_names[tid] = threading.current_thread().name
        ring = self._ring
        ring[next(self._seq) % len(ring)] = (
            name, tid, t0, dur, trace_id, args)
        s = self._sampler
        if s is not None:
            s.offer(name, tid, t0, dur, trace_id, args)

    def span(self, name: str, trace_id: Optional[int] = None,
             args: Optional[dict] = None):
        """Context manager timing its block into one record; the shared
        no-op instance when disabled."""
        if not self._enabled:
            return _NULL_SPAN
        return _Span(self, name, trace_id, args)

    def set_sampler(self, sampler) -> None:
        """Attach (or, with ``None``, detach) a tail sampler — an
        object whose ``offer(name, tid, t0, dur, trace_id, args)`` is
        called for every recorded span. ``tailsampling.TailSampler``
        is the in-tree one; ``clear()`` leaves the attachment alone."""
        self._sampler = sampler

    def sampler(self):
        return self._sampler

    # -- reading / export ---------------------------------------------------
    def __len__(self) -> int:
        return sum(1 for r in self._ring if r is not None)

    def records(self) -> List[Record]:
        """Chronological snapshot of the retained spans (<= capacity;
        the ring keeps the most recent ones once wrapped)."""
        recs = [r for r in self._ring if r is not None]
        recs.sort(key=lambda r: r[2])
        return recs

    def export_chrome_trace(self, path: str,
                            replica: Optional[str] = None) -> int:
        """Write the retained spans as Chrome trace-event JSON (the
        format Perfetto / ``chrome://tracing`` load). Returns the number
        of span events written. Timestamps are ``perf_counter``-relative
        microseconds — offsets within the trace are what matter.

        Every event carries this process's real ``pid`` and the export
        leads with a ``process_name`` metadata row (``replica`` arg,
        else the process replica label, else ``pid <n>``) — so N
        replicas' exports merged into one file
        (:func:`merge_chrome_traces`) render one labeled process track
        group each instead of collapsing into anonymous processes."""
        pid = os.getpid()
        label = replica if replica is not None else _replica
        # copy before iterating: recorder threads (pipeline workers, a
        # live coalescer) may register a first-seen tid mid-export —
        # iterating the live dict would raise and lose the whole trace
        events: List[dict] = [
            {"ph": "M", "pid": pid, "tid": 0, "name": "process_name",
             "args": {"name": label or f"pid {pid}"}}]
        events += [
            {"ph": "M", "pid": pid, "tid": tid, "name": "thread_name",
             "args": {"name": tname}}
            for tid, tname in sorted(self._tid_names.copy().items())]
        recs = self.records()
        for name, tid, t0, dur, trace_id, args in recs:
            ev = {"ph": "X", "pid": pid, "tid": tid, "name": name,
                  "cat": name.split(".", 1)[0],
                  "ts": round(t0 * 1e6, 3),
                  "dur": round(max(dur, 0.0) * 1e6, 3)}
            a = dict(args) if args else {}
            if trace_id is not None:
                a["trace_id"] = trace_id
            if a:
                ev["args"] = a
            events.append(ev)
        with open(path, "w") as f:
            # default=str: span args may carry numpy scalars etc.; a
            # lossy string beats a failed export
            json.dump({"traceEvents": events, "displayTimeUnit": "ms"},
                      f, default=str)
        return len(recs)


# -- the process-default tracer ---------------------------------------------

_tracer = Tracer(int(os.environ.get("QT_TRACE_CAPACITY",
                                    str(DEFAULT_CAPACITY))))


def get_tracer() -> Tracer:
    """The process-default :class:`Tracer` every in-tree hook records
    into."""
    return _tracer


def enabled() -> bool:
    return _tracer._enabled


def enable(capacity: Optional[int] = None) -> Tracer:
    return _tracer.enable(capacity)


def disable() -> Tracer:
    return _tracer.disable()


def clear() -> None:
    _tracer.clear()


def new_trace_id() -> int:
    return _tracer.new_trace_id()


def new_global_trace_id() -> int:
    return _tracer.new_global_trace_id()


# -- cross-process propagation ------------------------------------------------


def inject(carrier: Optional[dict] = None,
           trace_id: Optional[int] = None,
           parent: Optional[str] = None,
           replica: Optional[str] = None) -> dict:
    """Stamp a compact trace context into ``carrier`` (request
    metadata — any JSON-able dict; created when ``None``) and return
    it. ``trace_id`` defaults to a fresh GLOBAL id
    (:func:`new_global_trace_id` — pid-prefixed, collision-free across
    a fleet); ``replica`` defaults to this process's label. The
    receiving process hands the carrier to :func:`extract` (or to
    ``MicroBatchServer.submit(node_id, context=carrier)``) and its
    spans continue under the same ``trace_id``."""
    if carrier is None:
        carrier = {}
    carrier[CTX_TRACE_ID] = int(trace_id) if trace_id is not None \
        else new_global_trace_id()
    if parent is not None:
        carrier[CTX_PARENT] = str(parent)
    label = replica if replica is not None else _replica
    if label is not None:
        carrier[CTX_REPLICA] = str(label)
    return carrier


def extract(carrier) -> Optional[TraceContext]:
    """Read a trace context out of request metadata. Tolerant by
    design: ``None``, a non-dict, a dict without the context keys, or
    a mangled id all return ``None`` — a request without a usable
    context is simply untraced, never an error."""
    if not isinstance(carrier, dict):
        return None
    raw = carrier.get(CTX_TRACE_ID)
    try:
        tid = int(raw)
    except (TypeError, ValueError):
        return None
    parent = carrier.get(CTX_PARENT)
    replica = carrier.get(CTX_REPLICA)
    return TraceContext(tid,
                        str(parent) if parent is not None else None,
                        str(replica) if replica is not None else None)


def merge_chrome_traces(paths: Sequence[str], out_path: str) -> int:
    """Merge N per-process Chrome trace exports into ONE file Perfetto
    loads whole — the fleet view: one process track group per replica
    (each export's ``process_name`` metadata row names it), request
    spans correlated across groups by the propagated ``trace_id``.
    Two exports claiming the same pid (pid reuse across hosts or
    restarts) are disambiguated by offsetting the later file's pids —
    labels and intra-file structure are preserved. Returns the total
    number of events written. Files that fail to parse are skipped (a
    half-written export from a dying replica must not lose the rest
    of the fleet's trace)."""
    events: List[dict] = []
    used_pids: set = set()
    for p in paths:
        try:
            with open(p) as f:
                doc = json.load(f)
            evs = doc["traceEvents"] if isinstance(doc, dict) else doc
            if not isinstance(evs, list):
                continue
        except (OSError, ValueError, KeyError):
            continue
        file_pids = {e.get("pid") for e in evs
                     if isinstance(e, dict) and "pid" in e}
        remap: Dict[int, int] = {}
        for fp in sorted(x for x in file_pids if isinstance(x, int)):
            np_ = fp
            while np_ in used_pids:
                np_ += 1 << 22          # above the pid namespace
            remap[fp] = np_
            used_pids.add(np_)
        for e in evs:
            if not isinstance(e, dict):
                continue
            e = dict(e)
            if isinstance(e.get("pid"), int):
                e["pid"] = remap.get(e["pid"], e["pid"])
            events.append(e)
    with open(out_path, "w") as f:
        json.dump({"traceEvents": events, "displayTimeUnit": "ms"},
                  f, default=str)
    return len(events)


def record(name: str, t0: float, dur: float,
           trace_id: Optional[int] = None,
           args: Optional[dict] = None) -> None:
    _tracer.record(name, t0, dur, trace_id, args)


def span(name: str, trace_id: Optional[int] = None,
         args: Optional[dict] = None):
    return _tracer.span(name, trace_id, args)


def records() -> List[Record]:
    return _tracer.records()


def export_chrome_trace(path: str, replica: Optional[str] = None) -> int:
    return _tracer.export_chrome_trace(path, replica=replica)


# QT_TRACE=1 turns recording on; QT_TRACE=<path> additionally exports
# the ring to <path> at interpreter exit (the no-code-changes workflow:
# QT_TRACE=/tmp/trace.json python examples/serve_sage.py)
_env = os.environ.get("QT_TRACE", "")
if _env and _env.lower() not in ("0", "false", "no", "off"):
    _tracer.enable()
    if _env.lower() not in ("1", "true", "yes", "on"):
        atexit.register(_tracer.export_chrome_trace, _env)
