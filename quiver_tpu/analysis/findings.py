"""The one finding record shared by both verifier halves.

``qt_verify`` (and the test suite) consume findings from the jaxpr
verifier (``analysis.jaxpr_lint``) and the host-side AST verifier
(``analysis.host_lint``) through one shape: a rule id, a severity, the
entry point (or file) it anchors to, and a human message. ``record()``
is the ``lint``-kind JSONL payload the ``metrics.MetricsSink`` schema
carries (documented in docs/observability.md) — stdlib only, so the
host lint can run without paying a jax import.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Dict, List

ERROR = "ERROR"
WARN = "WARN"
INFO = "INFO"

_SEVERITY = {ERROR: 0, WARN: 1, INFO: 2}


@dataclass
class Finding:
    rule: str                 # rule id, e.g. "collective_divergence"
    level: str                # ERROR | WARN | INFO
    entry: str                # entry-point name or source path
    msg: str
    detail: Dict = field(default_factory=dict)

    def record(self) -> dict:
        """The ``lint``-kind JSONL payload (``MetricsSink`` adds ts)."""
        rec = {"kind": "lint", "rule": self.rule, "level": self.level,
               "entry": self.entry, "msg": self.msg}
        if self.detail:
            rec["detail"] = self.detail
        return rec

    def __str__(self) -> str:
        return f"{self.level} [{self.rule}] {self.entry}: {self.msg}"


def sort_findings(findings: List[Finding]) -> List[Finding]:
    """Severity-major, then entry/rule — the CLI's print order."""
    return sorted(findings,
                  key=lambda f: (_SEVERITY.get(f.level, 3), f.entry,
                                 f.rule, f.msg))


def has_errors(findings: List[Finding]) -> bool:
    return any(f.level == ERROR for f in findings)
