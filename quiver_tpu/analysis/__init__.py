"""Static analysis subsystem — ``scripts/qt_verify.py``'s engine.

Two halves:

- :mod:`~quiver_tpu.analysis.jaxpr_lint` — the jaxpr verifier: walks
  TRACED programs for host syncs, dishonored donation, divergent
  cond collectives, traffic-budget violations, and an executable
  census per registered entry point (imports jax).
- :mod:`~quiver_tpu.analysis.host_lint` — the AST verifier for
  host-side bug classes (lock-held sink emission, unfinalized thread
  resources, blocking syncs in ``@hot_path`` functions); stdlib only.

:mod:`~quiver_tpu.analysis.registry` declares the real entry points
(train/dist/e2e/serve builders, ``lookup_tiered``,
``dist_lookup_local``) with their budgets and census lattices. See
docs/analysis.md for the rule table and the ``lint`` JSONL schema.
"""

from . import host_lint  # noqa: F401  (stdlib-only half)
from .costmodel import CostModel, cost_of, cost_of_fn  # noqa: F401
from .findings import ERROR, INFO, WARN, Finding, has_errors, \
    sort_findings  # noqa: F401
from .jaxpr_lint import (CensusSpec, EntrySpec, RULES,  # noqa: F401
                         collective_payloads, divergent_cond_collectives,
                         gather_reads, host_sync_eqns, run_rules,
                         tier_read_bytes)
