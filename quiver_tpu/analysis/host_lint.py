"""Host-side AST verifier — the bug classes code review catches by hand.

Three rules, each a class of host-side defect a past review actually
flagged (PR 9's review notes), now checked mechanically over the source
tree. Stdlib ``ast`` only — no jax import, so this half of ``qt_verify``
runs in milliseconds and inside ``scripts/lint.sh``.

``lock_held_emit``     a JSONL sink emission (``*.emit(...)`` /
                       ``*.emit_stats(...)``) inside a ``with <lock>:``
                       block: a slow sink disk stalls every thread
                       contending on that hub/server lock (the PR 9 fix
                       moved all sink emission outside the locks —
                       this keeps it there).
``resource_finalizer`` a class that stores a ``threading.Thread`` /
                       ``Pipeline`` / ``ThreadPoolExecutor`` on
                       ``self`` must define ``close()``; a non-daemon
                       thread or an executor additionally needs a
                       ``weakref.finalize`` safety net (a ``Pipeline``
                       carries its own finalizer; a daemon thread dies
                       with the process and ``close()`` reaps it
                       deterministically).
``hot_path_blocking``  inside a function marked ``@hot_path``
                       (``quiver_tpu.profiling.hot_path``), no blocking
                       host sync: ``jax.device_get``,
                       ``.block_until_ready()``, ``.item()``/
                       ``.tolist()``, or ``np.asarray``/``np.array``
                       (all of which silently device_get a jax array).
``swallowed_worker_exception``
                       a bare / over-broad ``except`` (``except:``,
                       ``except Exception``, ``except BaseException``)
                       inside a ``while`` loop whose handler neither
                       re-raises, nor calls anything (logging, a
                       counter method, failing a future), nor mutates
                       state (a ``+= 1`` counter) — the worker-loop
                       swallow the fault injector keeps finding: the
                       loop looks healthy while silently dropping its
                       work. Count it, log it, or re-raise it.
"""

from __future__ import annotations

import ast
import pathlib
import re
from typing import Iterable, List, Optional

from .findings import ERROR, Finding

# resource constructors the lifecycle rule tracks: name -> whether the
# type carries its OWN weakref.finalize (Pipeline does — pipeline.py;
# ExtentReader binds one to its pool+fds — io.py; a class storing
# either must still define close() for deterministic shutdown)
_RESOURCES = {"Thread": False, "ThreadPoolExecutor": False,
              "Pipeline": True, "ExtentReader": True}

_BLOCKING_ATTRS = ("block_until_ready", "device_get", "item", "tolist")


def _call_name(func) -> str:
    """Trailing identifier of a call target: ``threading.Thread`` ->
    ``Thread``, ``Pipeline`` -> ``Pipeline``."""
    if isinstance(func, ast.Attribute):
        return func.attr
    if isinstance(func, ast.Name):
        return func.id
    return ""


_LOCK_NAME = re.compile(r"(^|_)locks?($|_)")


def _mentions_lock(expr) -> bool:
    """Does a with-item context expression name a lock? (``self._lock``,
    ``hub._lock``, ``self._counts_lock``, a bare ``lock`` variable.)
    Word-boundary match — ``block``/``blocking`` must not count."""
    for node in ast.walk(expr):
        name = None
        if isinstance(node, ast.Attribute):
            name = node.attr
        elif isinstance(node, ast.Name):
            name = node.id
        if name and _LOCK_NAME.search(name.lower()):
            return True
    return False


def _is_daemon_thread(call: ast.Call) -> bool:
    for kw in call.keywords:
        if kw.arg == "daemon" and isinstance(kw.value, ast.Constant):
            return bool(kw.value.value)
    return False


def _emit_calls(expr):
    """``*.emit*(...)`` calls inside one expression — pruning lambda
    bodies (they run later, not under the enclosing lock)."""
    stack = [expr]
    while stack:
        node = stack.pop()
        if isinstance(node, ast.Lambda):
            continue
        if isinstance(node, ast.Call) and isinstance(
                node.func, ast.Attribute) and \
                node.func.attr.startswith("emit"):
            yield node
        stack.extend(ast.iter_child_nodes(node))


def _check_lock_held_emit(tree, path: str) -> List[Finding]:
    out = []

    def flag(expr):
        for call in _emit_calls(expr):
            out.append(Finding(
                "lock_held_emit", ERROR, f"{path}:{call.lineno}",
                f"sink emission `{ast.unparse(call.func)}(...)` while "
                "holding a lock — a slow sink disk stalls every thread "
                "contending on it; queue under the lock, emit after "
                "release"))

    def scan(stmts, held):
        for node in stmts:
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                                 ast.ClassDef)):
                scan(node.body, False)     # runs later, lock released
                continue
            if isinstance(node, (ast.With, ast.AsyncWith)):
                inner = held or any(_mentions_lock(i.context_expr)
                                    for i in node.items)
                if held:
                    for i in node.items:
                        flag(i.context_expr)
                scan(node.body, inner)
                continue
            if held:
                # header expressions of this statement only — the
                # nested statement lists recurse below
                for _, value in ast.iter_fields(node):
                    vals = value if isinstance(value, list) else [value]
                    for v in vals:
                        if isinstance(v, ast.expr):
                            flag(v)
            # every nested statement list (if/for/try bodies, orelse,
            # finally, except handlers, match cases) keeps the lock
            for _, value in ast.iter_fields(node):
                if not isinstance(value, list) or not value:
                    continue
                if isinstance(value[0], ast.stmt):
                    scan(value, held)
                else:
                    for item in value:
                        body = getattr(item, "body", None)
                        if isinstance(body, list) and body and \
                                isinstance(body[0], ast.stmt):
                            scan(body, held)

    scan(tree.body, False)
    return out


def _walk_pruning_classes(node):
    """``ast.walk`` that does not descend into nested ClassDefs — a
    nested class's resources belong to ITS scan, not the outer one."""
    stack = list(ast.iter_child_nodes(node))
    while stack:
        n = stack.pop()
        yield n
        if not isinstance(n, ast.ClassDef):
            stack.extend(ast.iter_child_nodes(n))


def _self_stored_resources(cls):
    """Resource constructor calls a class actually STORES on self —
    directly (``self.x = Thread(...)``) or through a local that a
    later statement in the same method assigns to self
    (``t = Thread(...); ...; self._t = t``). A scoped worker that is
    joined and dropped cannot leak and is not collected."""
    created = []      # (resource_name, call_node)
    for fn in _walk_pruning_classes(cls):
        if not isinstance(fn, (ast.FunctionDef, ast.AsyncFunctionDef)):
            continue
        assigns = [n for n in ast.walk(fn) if isinstance(n, ast.Assign)]
        local_res = {}                  # local name -> (res, call)
        for node in assigns:            # pass 1: locals holding one
            if isinstance(node.value, ast.Call) and \
                    _call_name(node.value.func) in _RESOURCES:
                for t in node.targets:
                    if isinstance(t, ast.Name):
                        local_res[t.id] = (_call_name(node.value.func),
                                           node.value)
        for node in assigns:            # pass 2: what lands on self
            for t in node.targets:
                if not (isinstance(t, ast.Attribute) and isinstance(
                        t.value, ast.Name) and t.value.id == "self"):
                    continue
                v = node.value
                if isinstance(v, ast.Call) and \
                        _call_name(v.func) in _RESOURCES:
                    created.append((_call_name(v.func), v))
                elif isinstance(v, ast.Name) and v.id in local_res:
                    created.append(local_res[v.id])
    return [(name, call.lineno,
             not _RESOURCES[name] and not (name == "Thread"
                                           and _is_daemon_thread(call)))
            for name, call in created]


def _check_resource_finalizer(tree, path: str) -> List[Finding]:
    out = []
    for cls in ast.walk(tree):
        if not isinstance(cls, ast.ClassDef):
            continue
        has_close = False
        has_finalize = False
        for node in _walk_pruning_classes(cls):
            if isinstance(node, (ast.FunctionDef,
                                 ast.AsyncFunctionDef)) and \
                    node.name == "close":
                has_close = True
            if isinstance(node, ast.Call):
                name = _call_name(node.func)
                if name == "finalize" and isinstance(
                        node.func, ast.Attribute) and \
                        _call_name(node.func.value) in ("weakref",):
                    has_finalize = True
        created = _self_stored_resources(cls)
        if not created:
            continue
        names = sorted({n for n, _, _ in created})
        line = min(l for _, l, _ in created)
        if not has_close:
            out.append(Finding(
                "resource_finalizer", ERROR, f"{path}:{line}",
                f"class {cls.name} creates {'/'.join(names)} but "
                "defines no close() — the worker outlives the object "
                "across long runs; add idempotent close() (and a "
                "weakref.finalize safety net)"))
        elif any(nf for _, _, nf in created) and not has_finalize:
            bad = sorted({n for n, _, nf in created if nf})
            out.append(Finding(
                "resource_finalizer", ERROR, f"{path}:{line}",
                f"class {cls.name} creates {'/'.join(bad)} with no "
                "weakref.finalize safety net — an abandoned (never "
                "closed) instance leaks its worker; bind a finalizer "
                "to the resource (not self), or make the thread "
                "daemon=True with close() reaping it"))
    return out


def _hot_path_marked(fn) -> bool:
    for dec in fn.decorator_list:
        target = dec.func if isinstance(dec, ast.Call) else dec
        if _call_name(target) == "hot_path":
            return True
    return False


def _check_hot_path_blocking(tree, path: str) -> List[Finding]:
    out = []
    for fn in ast.walk(tree):
        if not isinstance(fn, (ast.FunctionDef, ast.AsyncFunctionDef)):
            continue
        if not _hot_path_marked(fn):
            continue
        for node in ast.walk(fn):
            if not isinstance(node, ast.Call):
                continue
            func = node.func
            if isinstance(func, ast.Attribute) and \
                    func.attr in _BLOCKING_ATTRS:
                what = f".{func.attr}()"
            elif isinstance(func, ast.Attribute) and \
                    func.attr in ("asarray", "array") and isinstance(
                        func.value, ast.Name) and \
                    func.value.id in ("np", "numpy"):
                what = f"np.{func.attr}(...)"
            else:
                continue
            out.append(Finding(
                "hot_path_blocking", ERROR, f"{path}:{node.lineno}",
                f"blocking host sync {what} inside @hot_path function "
                f"`{fn.name}` — the hot path must stay sync-free "
                "(device_get at the edges, never per step)"))
    return out


_BROAD_EXC = ("Exception", "BaseException")


def _is_broad_handler(handler: ast.ExceptHandler) -> bool:
    """bare ``except:`` or ``except Exception/BaseException``
    (including as one element of a tuple)."""
    t = handler.type
    if t is None:
        return True
    types = t.elts if isinstance(t, ast.Tuple) else [t]
    for e in types:
        name = e.attr if isinstance(e, ast.Attribute) else \
            getattr(e, "id", "")
        if name in _BROAD_EXC:
            return True
    return False


def _handler_reacts(handler: ast.ExceptHandler) -> bool:
    """Does the handler body count, log, or re-raise? Any ``raise``,
    any call (logging, a counter/stat method, failing a future), or
    any assignment/aug-assignment (``self.errors += 1``) counts as a
    reaction; ``pass``/``continue``/``break``/bare returns do not."""
    for node in ast.walk(handler):
        if isinstance(node, (ast.Raise, ast.Call, ast.AugAssign,
                             ast.Assign)):
            return True
    return False


def _check_swallowed_worker_exception(tree, path: str) -> List[Finding]:
    out = []

    def scan(node, in_loop):
        for child in ast.iter_child_nodes(node):
            if isinstance(child, (ast.FunctionDef, ast.AsyncFunctionDef,
                                  ast.ClassDef, ast.Lambda)):
                scan(child, False)      # a nested scope is its own loop
                continue
            # ``while`` loops only: a worker loop spins until told to
            # stop; a bounded ``for`` (shutdown best-effort sweeps,
            # result collection) retires its work either way, and its
            # swallow is judged by the surrounding code
            inner = in_loop or isinstance(child, ast.While)
            if isinstance(child, ast.ExceptHandler) and in_loop and \
                    _is_broad_handler(child) and \
                    not _handler_reacts(child):
                what = ("bare except" if child.type is None
                        else f"except {ast.unparse(child.type)}")
                out.append(Finding(
                    "swallowed_worker_exception", ERROR,
                    f"{path}:{child.lineno}",
                    f"{what} inside a worker loop neither counts, "
                    "logs, nor re-raises — the loop keeps spinning "
                    "while silently dropping its work (the class the "
                    "fault injector keeps finding); increment a "
                    "counter, log once, or re-raise"))
            scan(child, inner)

    scan(tree, False)
    return out


_CHECKS = (_check_lock_held_emit, _check_resource_finalizer,
           _check_hot_path_blocking, _check_swallowed_worker_exception)

HOST_RULES = ("lock_held_emit", "resource_finalizer",
              "hot_path_blocking", "swallowed_worker_exception")


def check_source(src: str, path: str = "<string>") -> List[Finding]:
    """Run every host-lint rule over one source string."""
    tree = ast.parse(src)
    out: List[Finding] = []
    for check in _CHECKS:
        out += check(tree, path)
    return out


def default_paths(root=".") -> List[pathlib.Path]:
    root = pathlib.Path(root)
    out = sorted((root / "quiver_tpu").rglob("*.py"))
    out += sorted((root / "scripts").glob("*.py"))
    return [p for p in out if "__pycache__" not in p.parts]


def run_host_lint(paths: Optional[Iterable] = None,
                  root=".") -> List[Finding]:
    """Host-lint a set of files (default: the library + scripts)."""
    out: List[Finding] = []
    for p in (paths if paths is not None else default_paths(root)):
        p = pathlib.Path(p)
        out += check_source(p.read_text(), str(p))
    return out
