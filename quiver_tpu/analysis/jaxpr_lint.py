"""Static jaxpr verifier — the repo's performance contract as rules.

The invariants that make the bandwidth-tiered gather + latency-hidden
sampling story hold on TPU (zero per-step host syncs, donated train
states, shard-uniform collective branching, dedup-bounded cold reads,
narrow exchange payloads, a flat executable cache) used to be enforced
by a patchwork: jaxpr walkers in ``tests/_traffic.py``, runtime phases
in ``scripts/check_leak.py``, greps in ``scripts/lint.sh``. This module
absorbs the walkers and generalizes them into a declarative rule
registry over *entry points* (an :class:`EntrySpec`: a traceable
callable + example args + the invariants it promises). Each rule walks
the TRACED program once — no compile, no timing, CPU-friendly — and
returns :class:`~quiver_tpu.analysis.findings.Finding` records.

Rules
-----
``no_host_sync``           no callback/infeed/outfeed equation anywhere
                           in the traced program (incl. ``pure_callback``
                           / ``io_callback`` / ``debug_callback`` — a
                           stray ``jax.debug.print`` in a metered step
                           is a per-step host round trip).
``donation_honored``       every ``donate_argnums`` buffer's (shape,
                           dtype) reappears among the outputs — drift
                           means XLA silently COPIES instead of reusing
                           the donated buffer (same class
                           ``_check_donatable`` guards at runtime, but
                           checked on the one shared trace).
``collective_divergence``  no collective (``all_to_all``/``psum``/
                           ``ppermute``/...) inside a ``lax.cond``
                           branch whose predicate is not uniform across
                           the mesh axis (not derived from a ``pmax``/
                           ``psum`` reduction) — divergent shards would
                           DEADLOCK the collective (PR 4's bug class).
``traffic_budget``         gathers on a declared tier's storage read at
                           most the declared row budget on the
                           unconditional path; compact-exchange
                           collectives ship at most the declared
                           fraction of the dense payload, and
                           dense-shaped payloads appear only inside
                           fallback (``lax.cond``) branches.
``executable_census``      the reachable jit-program set per entry
                           point, enumerated from declared DISCRETE
                           knob lattices, is finite and within a
                           declared cardinality — the static
                           precondition for cheap re-jit actuation
                           (ROADMAP item 4) and the flat-cache pins in
                           ``check_leak``.

The four walkers (``gather_reads``, ``tier_read_bytes``,
``host_sync_eqns``, ``collective_payloads``) keep their historical
signatures — ``tests/_traffic.py`` re-exports them so the existing
traffic pins run against THIS implementation and cannot drift.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Any, Callable, Dict, Optional, Sequence, Tuple

import numpy as np

import jax

from .findings import ERROR, INFO, Finding

try:
    _Literal = jax.core.Literal
except AttributeError:      # pragma: no cover - jax moved core
    from jax._src.core import Literal as _Literal


# host round-trip primitives: the structural definition of "this traced
# program syncs with the host" — callback-based syncs included
# (jax.debug.print lowers to debug_callback; jax.pure_callback /
# io_callback are the blocking data paths)
HOST_SYNC_PRIMS = ("io_callback", "pure_callback", "debug_callback",
                   "python_callback", "infeed", "outfeed")

# collectives that rendezvous across the mesh axis — any of these inside
# a divergent cond branch deadlocks the mesh
COLLECTIVE_PRIMS = ("all_to_all", "psum", "pmax", "pmin", "ppermute",
                    "all_gather", "reduce_scatter", "pgather")

# reductions whose output is, by construction, UNIFORM across the axis
MESH_REDUCE_PRIMS = ("psum", "pmax", "pmin")


# ---------------------------------------------------------------------------
# the walkers (absorbed from tests/_traffic.py — signatures preserved)
# ---------------------------------------------------------------------------


def _sub_jaxprs(eqn):
    """Every inner jaxpr a primitive's params carry (pjit/closed calls,
    shard_map's open jaxpr, scan bodies) EXCEPT cond branches — the
    walkers treat those specially to track fallback depth."""
    for name, sub in eqn.params.items():
        if eqn.primitive.name == "cond" and name == "branches":
            continue
        vals = sub if isinstance(sub, (tuple, list)) else (sub,)
        for v in vals:
            if hasattr(v, "jaxpr"):
                yield v.jaxpr
            elif hasattr(v, "eqns"):
                yield v


def _as_jaxpr(obj):
    """ClosedJaxpr | Jaxpr -> the open Jaxpr."""
    return obj.jaxpr if hasattr(obj, "jaxpr") else obj


def gather_reads(jaxpr, src_shape, dtype=None):
    """Gather equations reading an operand of ``src_shape`` (and
    optionally ``dtype``) anywhere in ``jaxpr`` (a ClosedJaxpr or inner
    jaxpr). Returns ``[(out_rows, cond_depth)]`` — ``cond_depth`` 0 for
    reads on the unconditional path, +1 per enclosing ``lax.cond``
    branch (fallback paths)."""
    jxp = _as_jaxpr(jaxpr)

    def walk(j, depth):
        out = []
        for eqn in j.eqns:
            if eqn.primitive.name == "cond":
                for br in eqn.params["branches"]:
                    out += walk(br.jaxpr, depth + 1)
            elif eqn.primitive.name == "gather":
                aval = eqn.invars[0].aval
                if tuple(aval.shape) == tuple(src_shape) and \
                        (dtype is None or aval.dtype == dtype):
                    out.append((eqn.outvars[0].aval.shape[0], depth))
            for sub in _sub_jaxprs(eqn):
                out += walk(sub, depth)
        return out

    return walk(jxp, 0)


def tier_read_bytes(fn, args, tier, max_depth=0):
    """Total bytes ``fn(*args)``'s traced program gathers from
    ``tier``'s storage at cond depth <= ``max_depth`` (default: only
    the always-taken narrow path). ``tier`` is a plain array or a
    quantized-tier pytree — sidecar reads count toward the total, so
    the byte comparison against an fp32 tier is honest."""
    jaxpr = jax.make_jaxpr(fn)(*args)
    # distinct (shape, dtype) specs, ONCE each: a quantized tier's
    # scale and zero share a spec, and counting per leaf would tally
    # each matching gather equation twice
    total = 0
    for shape, dt in _tier_specs(tier):
        width = int(np.prod(shape[1:])) * dt.itemsize
        for rows, depth in gather_reads(jaxpr, shape, dt):
            if depth <= max_depth:
                total += rows * width
    return total


def _tier_specs(tier):
    """Distinct (shape, dtype) storage specs of a tier pytree."""
    return {(tuple(leaf.shape), jax.numpy.dtype(leaf.dtype))
            for leaf in jax.tree_util.tree_leaves(tier)}


def host_sync_eqns(fn, args, prims=HOST_SYNC_PRIMS):
    """Every host-round-trip equation in the traced program — the
    structural pin that a jitted path performs ZERO per-step host
    syncs (the metrics counters must ride out as a plain device
    output, never via a callback). Returns ``[primitive_name]``;
    assert it is empty."""
    return host_sync_eqns_jaxpr(jax.make_jaxpr(fn)(*args), prims)


def host_sync_eqns_jaxpr(jaxpr, prims=HOST_SYNC_PRIMS):
    """:func:`host_sync_eqns` on an already-traced jaxpr."""
    def walk(j):
        out = []
        for eqn in j.eqns:
            if eqn.primitive.name in prims:
                out.append(eqn.primitive.name)
            if eqn.primitive.name == "cond":
                for br in eqn.params["branches"]:
                    out += walk(br.jaxpr)
            for sub in _sub_jaxprs(eqn):
                out += walk(sub)
        return out

    return walk(_as_jaxpr(jaxpr))


def collective_payloads(fn, args, prims=("all_to_all",),
                        with_depth=False):
    """Every collective equation's payload in the traced program —
    the exchange's wire traffic. Returns ``[(shape, dtype, bytes)]``
    (requests AND responses both appear; callers filter by shape/dtype
    when they want one direction). ``with_depth=True`` appends the
    ``lax.cond`` nesting depth as a fourth element (0 = the
    unconditional path; the compact exchange keeps BOTH its narrow
    collectives and the dense fallback inside one cond, so callers
    separate them by payload shape, and use depth to assert nothing
    dense-shaped leaked onto the unconditional path)."""
    return collective_payloads_jaxpr(jax.make_jaxpr(fn)(*args), prims,
                                     with_depth)


def collective_payloads_jaxpr(jaxpr, prims=("all_to_all",),
                              with_depth=False):
    """:func:`collective_payloads` on an already-traced jaxpr."""
    def walk(j, depth):
        out = []
        for eqn in j.eqns:
            if eqn.primitive.name in prims:
                aval = eqn.invars[0].aval
                rec = (tuple(aval.shape),
                       jax.numpy.dtype(aval.dtype),
                       int(np.prod(aval.shape)) * aval.dtype.itemsize)
                out.append(rec + (depth,) if with_depth else rec)
            if eqn.primitive.name == "cond":
                for br in eqn.params["branches"]:
                    out += walk(br.jaxpr, depth + 1)
            for sub in _sub_jaxprs(eqn):
                out += walk(sub, depth)
        return out

    return walk(_as_jaxpr(jaxpr), 0)


# ---------------------------------------------------------------------------
# mesh-uniformity dataflow (the collective_divergence rule's engine)
# ---------------------------------------------------------------------------


class _DivergenceWalk:
    """Track which values are UNIFORM across the mesh axis through the
    program, and flag every ``lax.cond`` that (a) contains a collective
    in a branch and (b) branches on a non-uniform predicate.

    Uniform sources: literals, closed-over constants, replicated
    ``shard_map`` inputs, and the outputs of ``psum``/``pmax``/``pmin``
    (a reduction OVER the axis is the same on every shard). Non-uniform
    sources: sharded ``shard_map`` inputs and ``axis_index``. Everything
    else propagates: an op's output is uniform iff every input is —
    ``local_flag & pmax_flag`` is still divergent, which is exactly the
    bug class this exists to catch."""

    def __init__(self):
        self.divergent = []     # (prims_in_branches, depth, source)
        self._flagged = set()   # cond eqn ids already reported (loop
        #                         bodies are re-walked to fix-point)

    # -- helpers -----------------------------------------------------------

    @staticmethod
    def _in_u(uniform, atom):
        if isinstance(atom, _Literal):
            return True
        return uniform.get(atom, True)

    def _bind(self, jaxpr, in_uniform):
        jxp = _as_jaxpr(jaxpr)
        uniform = {v: True for v in jxp.constvars}
        for v, u in zip(jxp.invars, in_uniform):
            uniform[v] = bool(u)
        return jxp, uniform

    # -- the walk ----------------------------------------------------------

    def walk(self, jaxpr, in_uniform, depth=0, in_mesh=False):
        """Returns ``(out_uniform, collectives)`` where ``collectives``
        is every ``(prim, depth)`` rendezvous reachable in this scope."""
        jxp, uniform = self._bind(jaxpr, in_uniform)
        collectives = []
        for eqn in jxp.eqns:
            name = eqn.primitive.name
            ins = [self._in_u(uniform, a) for a in eqn.invars]
            outs_u = all(ins)

            if name == "shard_map":
                body = eqn.params["jaxpr"]
                in_names = eqn.params.get("in_names") or ()
                body_in = [len(n) == 0 for n in in_names] \
                    if in_names else [False] * len(eqn.invars)
                _, sub_coll = self.walk(body, body_in, depth,
                                        in_mesh=True)
                collectives += sub_coll
                outs_u = True       # back outside the mesh

            elif name == "cond":
                pred_u = ins[0]
                br_outs, br_coll = [], []
                for br in eqn.params["branches"]:
                    o, c = self.walk(br, ins[1:], depth + 1, in_mesh)
                    br_outs.append(o)
                    br_coll += c
                if in_mesh and br_coll and not pred_u and \
                        id(eqn) not in self._flagged:
                    self._flagged.add(id(eqn))
                    self.divergent.append(
                        (sorted({p for p, _ in br_coll}), depth,
                         eqn.source_info))
                collectives += br_coll
                outs_u = None       # per-output below
                for i, v in enumerate(eqn.outvars):
                    uniform[v] = pred_u and all(
                        o[i] if i < len(o) else False for o in br_outs)

            elif name in MESH_REDUCE_PRIMS:
                if in_mesh:
                    collectives.append((name, depth))
                outs_u = True if in_mesh else all(ins)

            elif name in COLLECTIVE_PRIMS:
                if in_mesh:
                    collectives.append((name, depth))
                outs_u = False

            elif name == "axis_index":
                outs_u = not in_mesh

            elif name == "while":
                cc = eqn.params["cond_nconsts"]
                bc = eqn.params["body_nconsts"]
                carry = ins[cc + bc:]
                # iterate to a TRUE fix-point: one body pass only
                # narrows the carry one hop, and a rotation chain of
                # length k launders axis-dependence through k carries —
                # the lattice only descends, so this terminates within
                # len(carry) passes
                while True:
                    body_out, c = self.walk(
                        eqn.params["body_jaxpr"], ins[cc:cc + bc] + carry,
                        depth, in_mesh)
                    collectives += c
                    new_carry = [a and b
                                 for a, b in zip(carry, body_out)]
                    if new_carry == carry:
                        break
                    carry = new_carry
                _, c = self.walk(eqn.params["cond_jaxpr"],
                                 ins[:cc] + carry, depth, in_mesh)
                collectives += c
                outs_u = None
                for v, u in zip(eqn.outvars, carry):
                    uniform[v] = u

            elif name == "scan":
                nc = eqn.params["num_consts"]
                ncar = eqn.params["num_carry"]
                carry = ins[nc:nc + ncar]
                while True:
                    body_out, c = self.walk(
                        eqn.params["jaxpr"],
                        ins[:nc] + carry + ins[nc + ncar:], depth,
                        in_mesh)
                    collectives += c
                    new_carry = [a and b
                                 for a, b in zip(carry, body_out[:ncar])]
                    if new_carry == carry:
                        break
                    carry = new_carry
                outs_u = None
                for i, v in enumerate(eqn.outvars):
                    uniform[v] = carry[i] if i < ncar else \
                        (body_out[i] if i < len(body_out) else False)

            else:
                inner = None
                for k in ("jaxpr", "call_jaxpr", "fun_jaxpr"):
                    cand = eqn.params.get(k)
                    if cand is not None and (hasattr(cand, "jaxpr")
                                             or hasattr(cand, "eqns")):
                        inner = cand
                        break
                if inner is not None:
                    n_in = len(_as_jaxpr(inner).invars)
                    sub_in = ins if n_in == len(ins) \
                        else [all(ins)] * n_in
                    sub_out, c = self.walk(inner, sub_in, depth, in_mesh)
                    collectives += c
                    outs_u = None
                    for i, v in enumerate(eqn.outvars):
                        uniform[v] = sub_out[i] if i < len(sub_out) \
                            else all(ins)
                else:
                    # walk any other nested jaxprs conservatively (their
                    # conds still get checked; mapping is approximate)
                    for sub in _sub_jaxprs(eqn):
                        n_in = len(_as_jaxpr(sub).invars)
                        _, c = self.walk(sub, [all(ins)] * n_in, depth,
                                         in_mesh)
                        collectives += c

            if outs_u is not None:
                for v in eqn.outvars:
                    uniform[v] = outs_u
        return [self._in_u(uniform, v) for v in jxp.outvars], collectives


def divergent_cond_collectives(jaxpr):
    """Every ``lax.cond`` with collectives in a branch and a predicate
    that is NOT uniform across the mesh axis. Returns
    ``[(collective_prims, cond_depth, source_info)]`` — assert empty."""
    w = _DivergenceWalk()
    jxp = _as_jaxpr(jaxpr)
    w.walk(jxp, [True] * len(jxp.invars))
    return w.divergent


# ---------------------------------------------------------------------------
# entry points + the rule registry
# ---------------------------------------------------------------------------


@dataclass
class CensusSpec:
    """The declared reachable-executable lattice of one entry point.

    ``axes`` maps a knob name to its DISCRETE value lattice (any finite
    sequence) or directly to an int cardinality. A ``None`` (or any
    non-enumerable) axis means the knob is unbounded — the census rule
    ERRORs, because an unbounded knob means an unbounded executable
    cache and re-jit actuation is unsafe. The reachable program count
    is the product of the axis cardinalities and must not exceed
    ``max_programs``."""

    axes: Dict[str, Any]
    max_programs: int

    def axis_count(self, value) -> Optional[int]:
        if value is None or isinstance(value, bool):
            return None
        if isinstance(value, (str, bytes)):
            # a bare string is a typo'd one-element tuple, not a
            # lattice of its characters — refuse rather than miscount
            return None
        if isinstance(value, int):
            return value if value > 0 else None
        try:
            n = len(value)
        except TypeError:
            return None
        return n if n > 0 else None

    def count(self) -> Optional[int]:
        """Reachable program count, or None if any axis is unbounded."""
        total = 1
        for v in self.axes.values():
            n = self.axis_count(v)
            if n is None:
                return None
            total *= n
        return total


@dataclass
class EntrySpec:
    """One registered jitted hot path + the invariants it promises.

    ``fn``/``args`` give the single shared trace every rule walks.
    ``tier_budgets`` is a tuple of ``(tier, max_rows, max_depth)``: no
    gather on the tier's storage may read more than ``max_rows`` rows
    at cond depth <= ``max_depth``. ``exchange`` bounds collective
    payloads: ``{"prims": (...), "dense_bytes": int, "max_frac": f,
    "dense_shapes": (shape, ...)}``. ``rules=None`` runs every
    applicable rule."""

    name: str
    fn: Callable
    args: Tuple = ()
    donate_argnums: Tuple[int, ...] = ()
    sync_free: bool = True
    tier_budgets: Tuple = ()
    exchange: Optional[Dict] = None
    census: Optional[CensusSpec] = None
    rules: Optional[Sequence[str]] = None
    detail: Dict = field(default_factory=dict)
    _jaxpr: Any = field(default=None, repr=False)

    def jaxpr(self):
        """The one shared trace (cached — every rule walks this)."""
        if self._jaxpr is None:
            self._jaxpr = jax.make_jaxpr(self.fn)(*self.args)
        return self._jaxpr


def rule_no_host_sync(spec: EntrySpec):
    if not spec.sync_free:
        return []
    syncs = host_sync_eqns_jaxpr(spec.jaxpr())
    if not syncs:
        return []
    by_prim: Dict[str, int] = {}
    for p in syncs:
        by_prim[p] = by_prim.get(p, 0) + 1
    return [Finding(
        "no_host_sync", ERROR, spec.name,
        f"traced program performs {len(syncs)} host round trip(s): "
        + ", ".join(f"{p} x{n}" for p, n in sorted(by_prim.items()))
        + " — counters/telemetry must ride out as device outputs",
        {"primitives": by_prim})]


def rule_donation_honored(spec: EntrySpec):
    if not spec.donate_argnums:
        return []
    jaxpr = spec.jaxpr()
    spans, at = [], 0
    for a in spec.args:
        n = len(jax.tree_util.tree_leaves(a))
        spans.append((at, at + n))
        at += n
    out_pool: Dict[Tuple, int] = {}
    for aval in jaxpr.out_avals:
        k = (tuple(aval.shape), str(aval.dtype))
        out_pool[k] = out_pool.get(k, 0) + 1
    unmatched = []
    for argnum in spec.donate_argnums:
        lo, hi = spans[argnum]
        for aval in jaxpr.in_avals[lo:hi]:
            k = (tuple(aval.shape), str(aval.dtype))
            if out_pool.get(k, 0) > 0:
                out_pool[k] -= 1
            else:
                unmatched.append({"argnum": argnum, "shape": list(k[0]),
                                  "dtype": k[1]})
    if not unmatched:
        return []
    head = ", ".join(f"arg {u['argnum']}: {tuple(u['shape'])} "
                     f"{u['dtype']}" for u in unmatched[:4])
    return [Finding(
        "donation_honored", ERROR, spec.name,
        f"{len(unmatched)} donated buffer(s) have no same-shape/dtype "
        f"output to reuse ({head}) — XLA will silently copy instead of "
        "donating; fix the step to be shape/dtype-stable or drop "
        "donate_argnums",
        {"unmatched": unmatched})]


def rule_collective_divergence(spec: EntrySpec):
    out = []
    for prims, depth, src in divergent_cond_collectives(spec.jaxpr()):
        out.append(Finding(
            "collective_divergence", ERROR, spec.name,
            f"collective(s) {'/'.join(prims)} inside a lax.cond branch "
            f"(depth {depth}) whose predicate is NOT uniform across the "
            "mesh axis — shards can take different branches and "
            "deadlock the collective; pmax/psum-reduce the predicate "
            "over the axis first",
            {"collectives": list(prims), "cond_depth": depth}))
    return out


def rule_traffic_budget(spec: EntrySpec):
    out = []
    jaxpr = spec.jaxpr()
    for tier, max_rows, max_depth in spec.tier_budgets:
        for shape, dt in _tier_specs(tier):
            # SUMMED rows per storage component (each quantized-tier
            # leaf spec is checked separately — its sidecar gathers
            # mirror the data rows and must not double-count): a
            # regression that splits one budget-sized gather into two
            # still doubles tier traffic and must still flag
            reads = [r for r, d in gather_reads(jaxpr, shape, dt)
                     if d <= max_depth]
            total = sum(reads)
            if total > max_rows:
                out.append(Finding(
                    "traffic_budget", ERROR, spec.name,
                    f"gathers read {total} rows total "
                    f"({len(reads)} gather(s)) from the {shape} {dt} "
                    f"tier at cond depth <= {max_depth} — budget is "
                    f"{max_rows} rows (dedup/compaction bound "
                    "violated)",
                    {"rows": int(total), "budget": int(max_rows),
                     "tier_shape": list(shape),
                     "gathers": len(reads)}))
    ex = spec.exchange
    if ex:
        prims = tuple(ex.get("prims", ("all_to_all",)))
        payloads = collective_payloads_jaxpr(jaxpr, prims,
                                             with_depth=True)
        dense_shapes = {tuple(s) for s in ex.get("dense_shapes", ())}
        for shape, dt, nbytes, depth in payloads:
            if shape in dense_shapes and depth == 0:
                out.append(Finding(
                    "traffic_budget", ERROR, spec.name,
                    f"dense-shaped collective payload {shape} {dt} on "
                    "the UNCONDITIONAL path — dense exchange must live "
                    "only inside the lax.cond fallback",
                    {"shape": list(shape), "bytes": nbytes}))
        dense_bytes = ex.get("dense_bytes")
        max_frac = ex.get("max_frac", 0.25)
        if dense_bytes:
            # narrow payloads are separated by SHAPE, not depth: the
            # compact exchange keeps its narrow collectives INSIDE the
            # lax.cond (beside the dense fallback), so a depth filter
            # would sum to zero and never fire
            narrow = sum(b for s, _, b, _ in payloads
                         if tuple(s) not in dense_shapes)
            if narrow > max_frac * dense_bytes:
                out.append(Finding(
                    "traffic_budget", ERROR, spec.name,
                    f"compact-exchange payload is {narrow} bytes > "
                    f"{max_frac:.2f} x dense ({dense_bytes} bytes) — "
                    "the exchange is no longer narrow (cap "
                    "oversized?)",
                    {"narrow_bytes": int(narrow),
                     "dense_bytes": int(dense_bytes),
                     "max_frac": max_frac}))
    return out


def rule_executable_census(spec: EntrySpec):
    c = spec.census
    if c is None:
        return []
    out = []
    unbounded = [k for k, v in c.axes.items()
                 if c.axis_count(v) is None]
    if unbounded:
        return [Finding(
            "executable_census", ERROR, spec.name,
            f"knob axis/axes {', '.join(sorted(unbounded))} are "
            "UNBOUNDED — the reachable jit-program set cannot be "
            "enumerated, so the executable cache is not provably flat "
            "and re-jit actuation is unsafe; declare a finite discrete "
            "lattice",
            {"unbounded_axes": sorted(unbounded)})]
    n = c.count()
    if n > c.max_programs:
        out.append(Finding(
            "executable_census", ERROR, spec.name,
            f"census of {n} reachable programs exceeds the declared "
            f"bound of {c.max_programs} "
            f"(axes: {({k: c.axis_count(v) for k, v in c.axes.items()})})",
            {"count": n, "max_programs": c.max_programs}))
    out.append(Finding(
        "executable_census", INFO, spec.name,
        f"{n} reachable jit program(s) "
        f"(axes: {({k: c.axis_count(v) for k, v in c.axes.items()})}, "
        f"bound {c.max_programs})",
        {"count": n, "max_programs": c.max_programs}))
    return out


RULES: Dict[str, Callable] = {
    "no_host_sync": rule_no_host_sync,
    "donation_honored": rule_donation_honored,
    "collective_divergence": rule_collective_divergence,
    "traffic_budget": rule_traffic_budget,
    "executable_census": rule_executable_census,
}


def run_rules(spec: EntrySpec, rules: Optional[Sequence[str]] = None):
    """Run ``rules`` (default: the entry's own list, else all) against
    one entry point. Returns the findings list (possibly empty)."""
    names = rules or spec.rules or tuple(RULES)
    out = []
    for name in names:
        out += RULES[name](spec)
    return out
