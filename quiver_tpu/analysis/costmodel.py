"""Analytic cost model on the shared ``make_jaxpr`` trace — qt-prof's
"modeled" half.

``jaxpr_lint`` checks the traced program against *budgets* (may this
gather read more than N rows?); this module prices the same trace in
absolute units so the profiler (``quiver_tpu.profile``) can divide
modeled bytes by measured time and compare against the machine probe's
peaks — roofline efficiency per stage, no chip-time experiment needed.

One walk of the one shared trace per entry point (the same trace
``qt_verify``'s rules already take — no second ``make_jaxpr``) yields:

- **FLOPs** from the ``dot_general`` family (2 * out-elements * K per
  contraction — the model/apply cost);
- **gather bytes**: bytes every ``gather`` equation reads from its
  operand (the tiered-lookup and frontier-gather traffic), plus the
  bytes of the *index* operands feeding those gathers —
  ``gather_index_bytes``, the frontier-id round trip a fused
  sample+gather kernel (ROADMAP frontier 2) deletes. That number IS
  the fusion-headroom baseline: the intermediate buffer between sample
  and gather that never needs to touch HBM once the kernel lands.
- **collective bytes** (``all_to_all``/``all_gather``/... payloads —
  the exchange's wire cost, via the same accounting as
  ``collective_payloads``);
- **input/output bytes**: full reads of every entry argument *not*
  consumed through a gather (model params, CSR arrays a kernel scans)
  and the program's output writes;
- **per-tier bytes** for each tier the entry declares
  (``EntrySpec.tier_budgets``), via the shared ``gather_reads`` walker.

Control flow is priced honestly rather than optimistically:
``lax.scan`` bodies multiply by their trip count, ``lax.while`` bodies
count once and increment ``while_loops`` (unknown trip count — the
model is a floor there), and ``lax.cond`` contributes the elementwise
MINIMUM over its branches (a cond executes exactly one branch, so the
min is a true lower bound; the spread to the heaviest branch is
recorded as ``cond_extra_bytes`` so a narrow/fallback exchange still
shows its worst case). Efficiency computed from these bytes is
therefore conservative: the real program moves at least this much.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Dict, Tuple

import numpy as np

import jax

from .jaxpr_lint import (COLLECTIVE_PRIMS, EntrySpec, _Literal,
                         _as_jaxpr, _tier_specs, gather_reads)

#: cost fields the branch-min/branch-max fold runs over
_FIELDS = ("flops", "gather_bytes", "gather_index_bytes",
           "collective_bytes")


def _nbytes(aval) -> int:
    return int(np.prod(aval.shape)) * aval.dtype.itemsize


def _zero() -> Dict[str, float]:
    return {k: 0 for k in _FIELDS}


def _acc(a: Dict[str, float], b: Dict[str, float]) -> None:
    for k in _FIELDS:
        a[k] += b[k]


@dataclass
class CostModel:
    """The priced trace of one entry point / stage.

    All byte fields are LOWER bounds (cond -> min branch, while -> one
    trip); ``cond_extra_bytes`` carries the spread to the heaviest
    branch and ``while_loops`` the number of unknown-trip loops the
    floor ignores."""

    flops: int = 0
    gather_bytes: int = 0
    gather_index_bytes: int = 0   # the fusion-headroom baseline
    collective_bytes: int = 0
    input_bytes: int = 0          # non-gathered args, read in full
    output_bytes: int = 0
    tier_bytes: Dict[str, int] = field(default_factory=dict)
    cond_extra_bytes: int = 0
    while_loops: int = 0

    @property
    def total_bytes(self) -> int:
        """The roofline numerator: bytes the program must move at
        minimum — gathered rows + their index buffers + collective
        payloads + full reads of non-gathered inputs + output
        writes."""
        return int(self.gather_bytes + self.gather_index_bytes
                   + self.collective_bytes + self.input_bytes
                   + self.output_bytes)

    def record(self) -> dict:
        """JSONL-ready payload (the ``modeled`` block of a ``profile``
        record)."""
        rec = {
            "flops": int(self.flops),
            "gather_bytes": int(self.gather_bytes),
            "gather_index_bytes": int(self.gather_index_bytes),
            "collective_bytes": int(self.collective_bytes),
            "input_bytes": int(self.input_bytes),
            "output_bytes": int(self.output_bytes),
            "total_bytes": self.total_bytes,
        }
        if self.cond_extra_bytes:
            rec["cond_extra_bytes"] = int(self.cond_extra_bytes)
        if self.while_loops:
            rec["while_loops"] = int(self.while_loops)
        if self.tier_bytes:
            rec["tier_bytes"] = dict(self.tier_bytes)
        return rec


def _dot_flops(eqn) -> int:
    """2 * out-elements * K for one ``dot_general`` (K = contracted
    extent of the lhs)."""
    (lhs_c, _), _ = eqn.params["dimension_numbers"]
    lhs = eqn.invars[0].aval
    k = 1
    for d in lhs_c:
        k *= int(lhs.shape[d])
    out = int(np.prod(eqn.outvars[0].aval.shape))
    return 2 * out * k


def _pallas_spec_bytes(eqn) -> int:
    """DMA bytes of one ``pallas_call`` from its grid/block specs — the
    fallback pricing for kernels that publish no ``cost_estimate``.
    Blocked operands stream ``grid-steps x block`` bytes; ``ANY``-space
    operands (kernel-managed DMA, e.g. a whole CSR or feature table the
    kernel slices itself) are charged one full read — an upper bound for
    row-sparse kernels, but the model must not claim traffic below what
    the specs prove."""
    gm = eqn.params.get("grid_mapping")
    if gm is None:
        return sum(_nbytes(v.aval) for v in list(eqn.invars)
                   + list(eqn.outvars) if not isinstance(v, _Literal))
    try:
        steps = int(np.prod([int(g) for g in gm.grid])) if gm.grid else 1
    except TypeError:        # dynamic grid dim — floor at one pass
        steps = 1
    n_out = int(getattr(gm, "num_outputs", 0) or 0)
    bms = list(gm.block_mappings)
    total = 0
    for bm in bms[:len(bms) - n_out] if n_out else bms:
        sds = bm.array_shape_dtype
        full = int(np.prod(sds.shape)) * np.dtype(sds.dtype).itemsize
        if "any" in str(getattr(bm, "transformed_block_aval",
                                "")).lower():
            total += full
            continue
        blk = np.dtype(sds.dtype).itemsize
        for b, s in zip(bm.block_shape, sds.shape):
            try:
                blk *= int(s if b is None else b)
            except TypeError:
                blk *= int(s)
        total += steps * blk
    # outputs are written once in full (blocked out specs tile them)
    total += sum(_nbytes(v.aval) for v in eqn.outvars)
    return total


def _pallas_tier_rows(jaxpr, shape, dt) -> int:
    """Rows a ``pallas_call`` kernel reads from a tier leaf of
    ``(shape, dt)`` — the structural analogue of ``gather_reads`` for
    fused kernels, so ``tier_bytes`` stays a model output when the
    gather moves inside a kernel. Heuristic: when the leaf feeds a
    pallas_call as an operand, every float matrix OUTPUT whose row
    width matches the leaf's row width is one DMA'd tier row per row
    (exact for the fused hot-hop kernel, whose feature outputs are
    dequantized copies of the rows it pulled; sidecar leaves — row
    width 1 — match no output and price 0, an accepted undercount of
    8 B/row)."""
    jxp = _as_jaxpr(jaxpr)
    width = int(np.prod(shape[1:])) if len(shape) > 1 else 1
    rows = 0
    for eqn in jxp.eqns:
        if eqn.primitive.name == "pallas_call":
            feeds = any(
                not isinstance(v, _Literal)
                and tuple(getattr(v.aval, "shape", ())) == tuple(shape)
                and v.aval.dtype == dt
                for v in eqn.invars)
            if feeds and width > 1:
                for ov in eqn.outvars:
                    a = ov.aval
                    if (len(a.shape) >= 2
                            and np.issubdtype(a.dtype, np.floating)
                            and int(np.prod(a.shape[1:])) == width):
                        rows += int(a.shape[0])
            continue
        for k in ("jaxpr", "call_jaxpr", "fun_jaxpr", "body_jaxpr",
                  "cond_jaxpr"):
            sub = eqn.params.get(k)
            if sub is not None and (hasattr(sub, "jaxpr")
                                    or hasattr(sub, "eqns")):
                rows += _pallas_tier_rows(sub, shape, dt)
        for br in eqn.params.get("branches", ()) or ():
            rows += _pallas_tier_rows(br, shape, dt)
    return rows


class _CostWalk:
    """One recursive pricing pass; gather-operand vars and index vars
    are tracked across the whole walk and resolved through reshape/
    broadcast/convert chains AND inner-jaxpr boundaries (pjit,
    shard_map, cond branches) back to their origin, so one frontier-id
    buffer feeding two tier gathers counts once and a gathered entry
    argument is never ALSO priced as a full input read."""

    def __init__(self):
        self.gather_operands: set = set()   # origin ids gathers read
        self.index_origins: set = set()     # origin ids of index bufs
        self.extra_bytes = 0
        self.while_loops = 0
        self._alias: Dict[int, int] = {}    # var id -> parent var id

    def _origin(self, var) -> int:
        vid = id(var)
        seen = set()
        while vid in self._alias and vid not in seen:
            seen.add(vid)
            vid = self._alias[vid]
        return vid

    def _bind(self, inner, outer_invars) -> None:
        """Alias an inner jaxpr's invars to the outer equation's
        operands (1:1 positional — pjit/closed-call/shard_map/cond
        branches all satisfy this)."""
        inner_vars = _as_jaxpr(inner).invars
        if len(inner_vars) != len(outer_invars):
            return
        for iv, ov in zip(inner_vars, outer_invars):
            if not isinstance(ov, _Literal):
                self._alias[id(iv)] = id(ov)

    def walk(self, jaxpr, mult: int = 1) -> Dict[str, float]:
        jxp = _as_jaxpr(jaxpr)
        cost = _zero()
        for eqn in jxp.eqns:
            name = eqn.primitive.name

            if name == "dot_general":
                cost["flops"] += mult * _dot_flops(eqn)

            elif name == "gather":
                op, idx = eqn.invars[0], eqn.invars[1]
                cost["gather_bytes"] += mult * _nbytes(eqn.outvars[0].aval)
                self.gather_operands.add(self._origin(op))
                if not isinstance(idx, _Literal):
                    # index bytes accrue into the BRANCH-SCOPED cost
                    # (so the cond min/max fold applies — an index
                    # buffer only the fallback branch reads must not
                    # leak into the floor), deduped by origin so one
                    # frontier-id buffer feeding two gathers counts
                    # once
                    oid = self._origin(idx)
                    if oid not in self.index_origins:
                        self.index_origins.add(oid)
                        cost["gather_index_bytes"] += \
                            mult * _nbytes(idx.aval)

            elif name in COLLECTIVE_PRIMS:
                cost["collective_bytes"] += mult * _nbytes(
                    eqn.invars[0].aval)

            elif name == "pallas_call":
                # price the kernel's DMA traffic instead of recursing
                # into its body (the body jaxpr operates on refs — its
                # "gathers" are VMEM addressing, not HBM traffic, and
                # the old generic recursion mispriced them). Every
                # operand is kernel-consumed: streamed by block specs
                # or DMA'd row-wise, never ALSO a full input read.
                for v in eqn.invars:
                    if not isinstance(v, _Literal):
                        self.gather_operands.add(self._origin(v))
                ce = eqn.params.get("cost_estimate")
                if ce is not None:
                    # the kernel author's exact traffic model (the
                    # fused sample+gather hop publishes one) — and NO
                    # index bytes: frontier ids that stay in VMEM are
                    # exactly the traffic gather_index_bytes exists to
                    # expose, so a fused kernel reports 0 here as a
                    # model output, not an assertion
                    cost["flops"] += mult * int(
                        getattr(ce, "flops", 0) or 0)
                    cost["gather_bytes"] += mult * int(
                        getattr(ce, "bytes_accessed", 0) or 0)
                else:
                    cost["gather_bytes"] += mult * _pallas_spec_bytes(
                        eqn)
                continue

            if name == "cond":
                branches = []
                for br in eqn.params["branches"]:
                    self._bind(br, eqn.invars[1:])
                    branches.append(self.walk(br, mult))
                low = {k: min(b[k] for b in branches) for k in _FIELDS}
                high = {k: max(b[k] for b in branches) for k in _FIELDS}
                _acc(cost, low)
                self.extra_bytes += sum(
                    int(high[k] - low[k]) for k in _FIELDS
                    if k != "flops")
            elif name == "scan":
                length = int(eqn.params.get("length", 1))
                # body invars are consts + carry + per-iteration xs
                # slices, positionally 1:1 with the eqn operands —
                # bind them so a table gathered inside the loop is not
                # ALSO priced as a full input read
                self._bind(eqn.params["jaxpr"], eqn.invars)
                _acc(cost, self.walk(eqn.params["jaxpr"], mult * length))
            elif name == "while":
                self.while_loops += 1
                cc = int(eqn.params.get("cond_nconsts", 0))
                bc = int(eqn.params.get("body_nconsts", 0))
                carry = list(eqn.invars[cc + bc:])
                self._bind(eqn.params["body_jaxpr"],
                           list(eqn.invars[cc:cc + bc]) + carry)
                self._bind(eqn.params["cond_jaxpr"],
                           list(eqn.invars[:cc]) + carry)
                _acc(cost, self.walk(eqn.params["body_jaxpr"], mult))
                _acc(cost, self.walk(eqn.params["cond_jaxpr"], mult))
            elif name == "shard_map":
                # the body jaxpr is per-shard work; every shard of the
                # mesh runs it, and on the virtual CPU mesh (and any
                # single-host roofline) all of it moves through this
                # box's memory system
                mesh = eqn.params.get("mesh")
                n = int(getattr(mesh, "size", 1) or 1)
                self._bind(eqn.params["jaxpr"], eqn.invars)
                _acc(cost, self.walk(eqn.params["jaxpr"], mult * n))
            else:
                recursed = False
                for k in ("jaxpr", "call_jaxpr", "fun_jaxpr"):
                    sub = eqn.params.get(k)
                    if sub is not None and (hasattr(sub, "jaxpr")
                                            or hasattr(sub, "eqns")):
                        self._bind(sub, eqn.invars)
                        _acc(cost, self.walk(sub, mult))
                        recursed = True
                        break
                if not recursed and len(eqn.outvars) == 1:
                    # dataflow aliasing: an op whose every (non-literal)
                    # input resolves to ONE origin buffer yields a view/
                    # derivation of that buffer (reshape, broadcast,
                    # convert, the negative-index wrap's lt/add/select
                    # chain) — its output is still the same logical
                    # buffer for index/operand dedup purposes
                    origins = {self._origin(v) for v in eqn.invars
                               if not isinstance(v, _Literal)}
                    if len(origins) == 1:
                        self._alias[id(eqn.outvars[0])] = origins.pop()
        return cost


def cost_of_jaxpr(jaxpr, tiers: Tuple = ()) -> CostModel:
    """Price an already-traced (closed) jaxpr. ``tiers`` is an optional
    sequence of tier pytrees (``EntrySpec.tier_budgets`` storage
    arrays) to break gather traffic out per tier."""
    jxp = _as_jaxpr(jaxpr)
    w = _CostWalk()
    cost = w.walk(jxp)
    model = CostModel(
        flops=int(cost["flops"]),
        gather_bytes=int(cost["gather_bytes"]),
        gather_index_bytes=int(cost["gather_index_bytes"]),
        collective_bytes=int(cost["collective_bytes"]),
        cond_extra_bytes=int(w.extra_bytes),
        while_loops=w.while_loops,
    )
    # args never consumed through a gather are modeled as read in full
    # (model params, the CSR arrays sampling scans); gathered operands
    # are priced by their gathers and index args by gather_index_bytes
    # (origin resolution makes this hold across pjit boundaries and
    # reshape/convert chains)
    model.input_bytes = int(sum(
        _nbytes(v.aval) for v in jxp.invars
        if id(v) not in w.gather_operands
        and id(v) not in w.index_origins))
    out_avals = (jaxpr.out_avals if hasattr(jaxpr, "out_avals")
                 else [v.aval for v in jxp.outvars])
    model.output_bytes = int(sum(_nbytes(a) for a in out_avals))
    for tier in tiers:
        for shape, dt in _tier_specs(tier):
            width = int(np.prod(shape[1:])) * dt.itemsize
            rows = sum(r for r, d in gather_reads(jaxpr, shape, dt)
                       if d == 0)
            # gathers fused into a Pallas kernel leave no gather eqn —
            # recover their tier rows structurally
            rows += _pallas_tier_rows(jxp, shape, dt)
            key = f"{tuple(shape)}:{dt}"
            model.tier_bytes[key] = (model.tier_bytes.get(key, 0)
                                     + rows * width)
    return model


def cost_of(spec: EntrySpec) -> CostModel:
    """Price one registered entry point on its one shared trace (the
    same cached ``spec.jaxpr()`` the verifier rules walk)."""
    return cost_of_jaxpr(spec.jaxpr(),
                         tiers=tuple(t for t, _, _ in spec.tier_budgets))


def cost_of_fn(fn, args) -> CostModel:
    """Price an arbitrary traceable callable (used by the profiler's
    pipeline stages, which are not registry entries)."""
    return cost_of_jaxpr(jax.make_jaxpr(fn)(*args))
