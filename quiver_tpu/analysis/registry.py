"""The registered jitted hot paths ``qt_verify`` checks.

Every entry point the serving/training system can reach at runtime is
declared here as an :class:`~quiver_tpu.analysis.jaxpr_lint.EntrySpec`
builder: a small-CPU-shape instantiation of the REAL builder (same code
path production takes — ``build_train_step``, ``build_e2e_train_step``,
``build_dist_train_step``, ``build_dist_lookup_fn`` /
``dist_lookup_local``, ``build_serve_step`` via ``ServeEngine``,
``Feature.lookup_tiered``) plus the invariants it promises: sync-free,
donation-honored, shard-uniform branching, traffic budgets, and the
executable-census lattice. Shapes are tiny (tracing only — nothing
compiles), so the full registry runs in seconds on CPU.

Mesh entries trace over ALL visible devices — run under
``XLA_FLAGS=--xla_force_host_platform_device_count=8`` (the
tests/conftest.py convention; ``scripts/qt_verify.py`` sets it before
importing jax).

Registering a new entry point: write a builder returning an
``EntrySpec`` and call :func:`register_entry` (see docs/analysis.md).
Tests use the same hook to register seeded-violation entries.
"""

from __future__ import annotations

import functools
from types import SimpleNamespace
from typing import Callable, Dict, List, Optional

from .jaxpr_lint import CensusSpec, EntrySpec, run_rules

# name -> (builder, quick): quick entries form the mini matrix
# ``qt_verify --quick`` (and scripts/lint.sh) runs
_REGISTRY: Dict[str, tuple] = {}


def register_entry(name: str, builder: Callable[[], EntrySpec],
                   quick: bool = False) -> None:
    _REGISTRY[name] = (builder, quick)


def entry_names(quick: bool = False) -> List[str]:
    return [n for n, (_, q) in _REGISTRY.items() if q or not quick]


def build_entry_specs(name: str) -> List[EntrySpec]:
    """ALL specs of one entry — a builder may return several so every
    point of its census lattice (each fanout variant, each jit arity)
    is actually traced by the rules, not just a representative."""
    if name not in _REGISTRY:
        raise KeyError(
            f"unknown entry point {name!r}; registered: "
            f"{', '.join(sorted(_REGISTRY))}")
    built = _REGISTRY[name][0]()
    return list(built) if isinstance(built, (list, tuple)) else [built]


def build_entry(name: str) -> EntrySpec:
    """The entry's primary spec (the one carrying its census)."""
    return build_entry_specs(name)[0]


def run_registry(names: Optional[List[str]] = None,
                 quick: bool = False):
    """Build + verify entries; returns ``(findings, entries_run)``."""
    findings, ran = [], []
    for name in (names or entry_names(quick=quick)):
        for spec in build_entry_specs(name):
            findings += run_rules(spec)
        ran.append(name)
    return findings, ran


# ---------------------------------------------------------------------------
# shared small-shape fixture (built once per process)
# ---------------------------------------------------------------------------


@functools.lru_cache(maxsize=None)
def _fixture() -> SimpleNamespace:
    import numpy as np
    import jax
    import jax.numpy as jnp
    import optax

    from ..models import GraphSAGE
    from ..ops.sample_multihop import sample_multihop
    from ..parallel.train import (init_state, layers_to_adjs,
                                  masked_feature_gather)

    rng = np.random.default_rng(0)
    n, dim, bs, sizes = 256, 16, 8, [3, 2]
    deg = rng.integers(1, 6, n).astype(np.int64)
    indptr = np.zeros(n + 1, np.int64)
    np.cumsum(deg, out=indptr[1:])
    indices = rng.integers(0, n, int(indptr[-1]), dtype=np.int32)
    indptr_j = jnp.asarray(indptr.astype(np.int32))
    indices_j = jnp.asarray(indices)
    feat = jnp.asarray(rng.standard_normal((n, dim)).astype(np.float32))
    labels = jnp.asarray(rng.integers(0, 4, n).astype(np.int32))
    model = GraphSAGE(hidden_dim=8, out_dim=4, num_layers=2, dropout=0.0)
    tx = optax.adam(1e-3)
    seeds = jnp.arange(bs, dtype=jnp.int32)
    n_id, layers = sample_multihop(indptr_j, indices_j, seeds, sizes,
                                   jax.random.key(0))
    state = init_state(model, tx, masked_feature_gather(feat, n_id),
                       layers_to_adjs(layers, bs, sizes),
                       jax.random.key(1))
    return SimpleNamespace(n=n, dim=dim, bs=bs, sizes=sizes,
                           indptr_np=indptr, indices_np=indices,
                           indptr=indptr_j, indices=indices_j,
                           feat=feat, labels=labels, model=model,
                           tx=tx, seeds=seeds, state=state)


def _mesh(axis: str):
    import numpy as np
    import jax
    from jax.sharding import Mesh
    return Mesh(np.array(jax.devices()), (axis,))


def _frontier_cap(batch: int, sizes) -> int:
    from ..pyg.sage_sampler import layer_shapes
    return layer_shapes(batch, sizes)[-1].n_id_cap


# ---------------------------------------------------------------------------
# the entries
# ---------------------------------------------------------------------------


def _train_step() -> EntrySpec:
    import jax
    from ..parallel import build_train_step
    fx = _fixture()
    step = build_train_step(fx.model, fx.tx, fx.sizes, fx.bs,
                            dedup_gather=True, collect_metrics=True)
    args = (fx.state, fx.feat, None, fx.indptr, fx.indices, fx.seeds,
            fx.labels[fx.seeds], jax.random.key(2))
    return EntrySpec(
        name="train_step", fn=step.jitted_fns[0], args=args,
        donate_argnums=(0,),
        census=CensusSpec({"program": ("fused",)}, max_programs=1))


def _lookup_tiered() -> EntrySpec:
    import numpy as np
    import jax.numpy as jnp
    from ..feature import Feature
    from ..utils import CSRTopo
    fx = _fixture()
    budget = 64
    topo = CSRTopo(indptr=fx.indptr_np, indices=fx.indices_np)
    store = Feature(device_cache_size=(fx.n // 4) * fx.dim * 4,
                    csr_topo=topo, dedup_cold=True, cold_budget=budget)
    store.from_cpu_tensor(np.asarray(fx.feat))
    host = jnp.asarray(store.host_part)
    ids = jnp.asarray(
        np.random.default_rng(1).integers(0, fx.n, 128, dtype=np.int32))
    raw = store._lookup_tiered_raw

    def fn(dev_part, host_part, ids_, order):
        # the driven lattice: unmasked, metered — phase 5/9's path
        return raw(dev_part, host_part, ids_, order, False, True)

    return EntrySpec(
        name="lookup_tiered", fn=fn,
        args=(store.device_part, host, ids, store.feature_order),
        tier_budgets=((host, budget, 0),),
        census=CensusSpec({"masked": (False,), "collect": (True,)},
                          max_programs=1),
        detail={"cold_budget": budget})


def _dist_lookup() -> EntrySpec:
    import numpy as np
    import jax
    import jax.numpy as jnp
    from ..comm import build_dist_lookup_fn
    fx = _fixture()
    h = len(jax.devices())
    rows, batch, cap = 32, 64, 8
    mesh = _mesh("host")
    fn = build_dist_lookup_fn(mesh, "host", rows, batch,
                              exchange_cap=cap, collect_metrics=True,
                              merge_counters=True)
    total = h * rows
    rng = np.random.default_rng(2)
    ids = jnp.asarray(rng.integers(0, total, h * batch, dtype=np.int32))
    g2h = jnp.asarray((np.arange(total) // rows).astype(np.int32))
    loc = jnp.asarray((np.arange(total) % rows).astype(np.int32))
    feat = jnp.asarray(
        rng.standard_normal((total, fx.dim)).astype(np.float32))
    dense_bytes = h * batch * 4 + h * batch * fx.dim * 4
    return EntrySpec(
        name="dist_lookup", fn=fn, args=(ids, g2h, loc, feat),
        exchange={"prims": ("all_to_all",),
                  "dense_bytes": dense_bytes, "max_frac": 0.25,
                  "dense_shapes": ((h, batch), (h, batch, fx.dim))},
        census=CensusSpec({"program": ("fused",)}, max_programs=1),
        detail={"exchange_cap": cap, "batch_per_host": batch})


def _serve_step() -> List[EntrySpec]:
    import jax
    from ..serving import ServeEngine
    fx = _fixture()
    engine = ServeEngine(fx.model, fx.state.params,
                         (fx.indptr, fx.indices), fx.feat,
                         sizes_variants=[[3, 2], [2, 1], [1, 1]],
                         batch_cap=16, dedup_gather=True,
                         collect_metrics=True)
    seeds = engine.pad_seeds(list(range(8)))
    args = (engine.params, engine._key, engine._feat, engine._forder,
            engine._indptr, engine._indices,
            jax.numpy.asarray(seeds))
    census = CensusSpec({"fanout_variant": tuple(
        tuple(v) for v in engine.variants)}, max_programs=4)
    # EVERY ladder variant is traced (a host sync introduced only in
    # the shed variant must not slip past the verifier); the census
    # rides the primary spec once
    return [EntrySpec(
        name="serve_step" if v == 0 else f"serve_step[variant{v}]",
        fn=step, args=args,
        donate_argnums=(1,),        # the threaded PRNG key chain
        census=census if v == 0 else None,
        detail={"batch_cap": engine.batch_cap,
                "fanout": engine.variants[v]})
        for v, step in enumerate(engine._steps)]


def _sharded_serve_step() -> List[EntrySpec]:
    import numpy as np
    import jax
    from ..feature import DistFeature, PartitionInfo
    from ..comm import TpuComm
    from ..serving import ShardedServeEngine
    fx = _fixture()
    h = len(jax.devices())
    cap = 16
    mesh = _mesh("host")
    # identity partition: global id g lives at (host g//rows, row g%rows)
    rows = fx.n // h
    g2h = (np.arange(fx.n) // rows).astype(np.int32)
    info = PartitionInfo(host=0, hosts=h, global2host=g2h)
    comm = TpuComm(rank=0, world_size=h, mesh=mesh, axis="host")
    dist = DistFeature.from_partition(np.asarray(fx.feat), info, comm,
                                      exchange_cap=cap)
    engine = ShardedServeEngine(fx.model, fx.state.params,
                                (fx.indptr, fx.indices), dist,
                                sizes_variants=[[3, 2], [2, 1], [1, 1]],
                                batch_cap=16, home=0,
                                collect_metrics=True)
    seeds = jax.numpy.asarray(engine.pad_seeds(list(range(8))))
    args = (engine.params, engine._key, dist._spmd_feat, engine._g2h,
            engine._g2l, engine._indptr, engine._indices, seeds)
    census = CensusSpec({"fanout_variant": tuple(
        tuple(v) for v in engine.variants)}, max_programs=4)

    def budget(sizes):
        frontier = _frontier_cap(engine.batch_cap, sizes)
        dense = h * frontier * 4 + h * frontier * fx.dim * 4
        return {"prims": ("all_to_all",), "dense_bytes": dense,
                "max_frac": 0.25,
                "dense_shapes": ((h, frontier), (h, frontier, fx.dim))}

    # EVERY ladder variant is traced (each is its own shard_map program
    # over the partitioned store); the census rides the primary once
    return [EntrySpec(
        name="sharded_serve_step" if v == 0
        else f"sharded_serve_step[variant{v}]",
        fn=step, args=args,
        donate_argnums=(1,),        # the threaded PRNG key chain
        exchange=budget(engine.variants[v]),
        census=census if v == 0 else None,
        detail={"batch_cap": engine.batch_cap, "exchange_cap": cap,
                "home": engine.home, "fanout": engine.variants[v]})
        for v, step in enumerate(engine._steps)]


def _rows_view():
    """The exact-mode wide-path layout view of the fixture's indices
    (what callers pass as ``indices_rows``) — lets the rows arity of
    the shard_map builders be traced, not just declared in the
    census."""
    from ..ops import as_index_rows
    return as_index_rows(_fixture().indices)


def _e2e_train_step() -> List[EntrySpec]:
    import jax
    from ..parallel import build_e2e_train_step
    fx = _fixture()
    h = len(jax.devices())
    mesh = _mesh("data")
    per_dev = 4
    step = build_e2e_train_step(fx.model, fx.tx, fx.sizes, per_dev,
                                mesh, dedup_gather=True,
                                collect_metrics=True,
                                merge_counters=True)
    seeds = jax.numpy.arange(h * per_dev, dtype=jax.numpy.int32)
    args = (fx.state, fx.feat, None, fx.indptr, fx.indices, seeds,
            fx.labels[seeds], jax.random.key(3))
    census = CensusSpec({"rows_arity": (False, True)}, max_programs=2)
    return [
        EntrySpec(name="e2e_train_step", fn=step.jitted_fns[1],
                  args=args, donate_argnums=(0,), census=census),
        # the with-rows arity (wide-exact path) is its own program —
        # trace it too so both census points are actually verified
        EntrySpec(name="e2e_train_step[rows]", fn=step.jitted_fns[0],
                  args=args + (_rows_view(),), donate_argnums=(0,))]


def _dist_train_step() -> EntrySpec:
    import numpy as np
    import jax
    import jax.numpy as jnp
    from ..parallel import build_dist_train_step
    fx = _fixture()
    h = len(jax.devices())
    mesh = _mesh("host")
    rows = fx.n // h
    per_host, cap = 4, 8
    step = build_dist_train_step(fx.model, fx.tx, fx.sizes, per_host,
                                 mesh, rows_per_host=rows,
                                 exchange_cap=cap,
                                 collect_metrics=True,
                                 merge_counters=True)
    # identity partition: global id g lives at (host g//rows, row g%rows)
    g2h = jnp.asarray((np.arange(fx.n) // rows).astype(np.int32))
    g2l = jnp.asarray((np.arange(fx.n) % rows).astype(np.int32))
    seeds = jnp.arange(h * per_host, dtype=jnp.int32)
    args = (fx.state, fx.feat, g2h, g2l, fx.indptr, fx.indices, seeds,
            fx.labels[seeds], jax.random.key(4))
    frontier = _frontier_cap(per_host, fx.sizes)
    dense_bytes = h * frontier * 4 + h * frontier * fx.dim * 4
    exchange = {"prims": ("all_to_all",),
                "dense_bytes": dense_bytes, "max_frac": 0.25,
                "dense_shapes": ((h, frontier), (h, frontier, fx.dim))}
    detail = {"exchange_cap": cap, "frontier_cap": frontier}
    return [
        EntrySpec(name="dist_train_step",
                  fn=step.jitted_fns[1],    # the no-indices_rows arity
                  args=args, donate_argnums=(0,), exchange=exchange,
                  census=CensusSpec({"rows_arity": (False, True)},
                                    max_programs=2),
                  detail=detail),
        EntrySpec(name="dist_train_step[rows]", fn=step.jitted_fns[0],
                  args=args + (_rows_view(),), donate_argnums=(0,),
                  exchange=exchange, detail=detail)]


def _fused_hot_hop() -> List[EntrySpec]:
    import numpy as np
    import jax.numpy as jnp
    from ..ops import quant
    from ..ops.pallas.fused import (default_interpret, fused_hot_hop,
                                    pad_indices)
    fx = _fixture()
    k, row_cap = 4, 64
    rng = np.random.default_rng(3)
    # dedicated lane-aligned table: per-row feature DMAs need the row
    # width to be a multiple of 128 (the fixture's dim-16 table would
    # trip the full-table pad cliff on every call)
    wide = jnp.asarray(
        rng.standard_normal((fx.n, 128)).astype(np.float32))
    feat_q = quant.quantize(wide, "int8")
    idx = pad_indices(fx.indices, row_cap)
    interpret = default_interpret()

    def make(feat):
        def fn(indptr, indices_padded, seeds, seed):
            # the portable "hash" rng: the entry is executable (the
            # profiler runs registry entries) and bit-compatible with
            # the split oracle on every backend
            return fused_hot_hop(indptr, indices_padded, seeds, feat,
                                 k, seed, row_cap=row_cap, rng="hash",
                                 interpret=interpret)
        return fn

    args = (fx.indptr, idx, fx.seeds, jnp.int32(7))
    # rows the kernel DMAs from the tier per call: one padded seed
    # block plus its picks (no gather eqn exists to meter — the budget
    # bounds the structural _pallas_tier_rows count via costmodel)
    budget = 128 * (1 + k)
    return [
        EntrySpec(
            name="fused_hot_hop", fn=make(feat_q), args=args,
            tier_budgets=((feat_q, budget, 0),),
            census=CensusSpec({"variant": ("quantized", "plain")},
                              max_programs=2),
            detail={"k": k, "row_cap": row_cap, "rng": "hash"}),
        # the plain-f32 tier variant is its own program — trace it too
        # so both census points are actually verified
        EntrySpec(
            name="fused_hot_hop[plain]", fn=make(wide), args=args,
            tier_budgets=((wide, budget, 0),))]


def _fused_multihop() -> List[EntrySpec]:
    import numpy as np
    import jax
    import jax.numpy as jnp
    from ..ops import quant
    from ..ops.pallas.fused import (default_interpret, fused_multihop,
                                    pad_indices)
    fx = _fixture()
    sizes, row_cap = [3, 2], 64
    rng = np.random.default_rng(3)
    # same lane-aligned dim-128 table as the single-hop entry (per-row
    # feature DMAs need a multiple-of-128 row width)
    wide = jnp.asarray(
        rng.standard_normal((fx.n, 128)).astype(np.float32))
    feat_q = quant.quantize(wide, "int8")
    idx = pad_indices(fx.indices, row_cap)
    interpret = default_interpret()

    def make(feat):
        def fn(indptr, indices_padded, seeds, key):
            # the whole fused walk — interior sampling-only hops,
            # leaf sample+gather hop, gather-free compaction and the
            # frontier-block reassembly: the multi-hop train/serve
            # front-end whose modeled gather_index_bytes must be 0
            return fused_multihop(indptr, indices_padded, seeds, feat,
                                  sizes, key, row_cap=row_cap,
                                  rng="hash", interpret=interpret)
        return fn

    args = (fx.indptr, idx, fx.seeds, jax.random.key(11))
    # tier rows the LEAF kernel DMAs per call: its seed block is the
    # hop-0 frontier cap (8 * (1+3) = 32) padded to one 128-seed grid
    # block, each block reading (1 + k_leaf) rows per seed; interior
    # hops never touch the tier
    budget = 128 * (1 + sizes[-1])
    return [
        EntrySpec(
            name="fused_multihop", fn=make(feat_q), args=args,
            tier_budgets=((feat_q, budget, 0),),
            census=CensusSpec({"variant": ("quantized", "plain")},
                              max_programs=2),
            detail={"sizes": tuple(sizes), "row_cap": row_cap,
                    "rng": "hash"}),
        EntrySpec(
            name="fused_multihop[plain]", fn=make(wide), args=args,
            tier_budgets=((wide, budget, 0),))]


register_entry("train_step", _train_step, quick=True)
register_entry("lookup_tiered", _lookup_tiered, quick=True)
register_entry("dist_lookup", _dist_lookup, quick=True)
register_entry("serve_step", _serve_step, quick=True)
register_entry("sharded_serve_step", _sharded_serve_step, quick=True)
register_entry("fused_hot_hop", _fused_hot_hop, quick=True)
register_entry("fused_multihop", _fused_multihop, quick=True)
register_entry("e2e_train_step", _e2e_train_step)
register_entry("dist_train_step", _dist_train_step)
