"""PyG-compatible k-hop neighbor samplers, TPU-native.

Re-provides the capabilities of the reference ``GraphSageSampler`` /
``MixedGraphSageSampler`` / ``SampleJob`` (pyg/sage_sampler.py:40-375) with
a jit-first design:

- the whole multi-hop sample (every layer's sample + compaction) is ONE
  jitted XLA program per (batch_size,) — the reference crosses the
  Python->C++ boundary twice per layer (survey §3.1); here there are zero
  per-layer host round trips.
- output shapes are static (capacity + valid counts); invalid slots hold
  -1. ``Adj.size`` reports capacities; masks derive from ``edge_index >= 0``.
- modes: ``HBM`` (topology resident in device HBM, ≈ reference GPU/DMA),
  ``HOST`` (topology in host memory, device pulls on demand, ≈ UVA
  zero-copy), ``CPU`` (sampling on host CPU via the native C++ engine).
- RNG is an explicit, reproducible key chain instead of ad-hoc per-thread
  curand seeds (quiver.cu.hpp:129-135).
"""

from __future__ import annotations

import time
from typing import Generic, List, NamedTuple, Sequence, TypeVar

import jax
import jax.numpy as jnp
import numpy as np

from ..ops.sample import compact_layer, sample_layer, sample_prob
from ..utils import CSRTopo

T_co = TypeVar("T_co", covariant=True)


from ..utils.placement import pinned_put as _pinned_put  # shared helper


@jax.tree_util.register_pytree_node_class
class Adj:
    """One message-passing hop, PyG orientation (source -> target).

    edge_index: [2, cap_edges] int32, -1 fill; row 0 = source (neighbor)
                local id, row 1 = target (seed) local id.
    e_id:       [cap_edges] global edge id per sampled edge (-1 fill)
                when the sampler tracks edge ids
                (``GraphSageSampler(..., with_eid=True)``); ``None``
                otherwise (the reference ships the same shape empty,
                sage_sampler.py:143).
    mask:       [cap_edges] bool validity of each edge slot (equivalent
                to ``edge_index[0] >= 0``; kept explicit so consumers
                don't have to rederive it).
    size:       (cap_source_nodes, cap_target_nodes) static capacities —
                pytree aux data, so Adjs cross jit boundaries safely.

    Supports PyG-style destructuring: ``edge_index, e_id, size = adj``.
    """

    __slots__ = ("edge_index", "e_id", "size", "mask")

    def __init__(self, edge_index, e_id, size, mask=None):
        self.edge_index = edge_index
        self.e_id = e_id
        self.size = tuple(size)
        self.mask = mask if mask is not None else edge_index[0] >= 0

    def __iter__(self):
        return iter((self.edge_index, self.e_id, self.size))

    def to(self, *args, **kwargs):  # API compat; placement is explicit in jax
        return self

    def tree_flatten(self):
        return (self.edge_index, self.e_id, self.mask), self.size

    @classmethod
    def tree_unflatten(cls, size, leaves):
        return cls(leaves[0], leaves[1], size, leaves[2])


class _LayerShape(NamedTuple):
    num_seeds: int
    fanout: int
    n_id_cap: int


def layer_shapes(batch_size: int, sizes: Sequence[int]) -> List[_LayerShape]:
    shapes = []
    s = batch_size
    for k in sizes:
        cap = s + s * k
        shapes.append(_LayerShape(num_seeds=s, fanout=k, n_id_cap=cap))
        s = cap
    return shapes


class GraphSageSampler:
    """k-hop sampler returning ``(n_id, batch_size, adjs)`` like PyG's
    ``NeighborSampler`` (reference: sage_sampler.py:118-147)."""

    def __init__(self, csr_topo: CSRTopo, sizes: Sequence[int],
                 device=None, mode: str = "HBM", seed: int = 0,
                 edge_weight=None, sampling: str = "exact",
                 with_eid: bool = False, layout: str = "pair",
                 shuffle: str = "sort", allow_fallback: bool = True,
                 wide_exact: bool = True,
                 collect_metrics: bool = False):
        if mode not in ("HBM", "HOST", "CPU", "UVA", "GPU"):
            raise ValueError(f"unknown sampler mode {mode!r}")
        # accept reference mode names: UVA -> HOST tier, GPU -> HBM
        mode = {"UVA": "HOST", "GPU": "HBM"}.get(mode, mode)
        self.mode = mode
        self.sizes = list(sizes)
        self.csr_topo = csr_topo
        self.device = device
        # CSR-slot-aligned edge weights => weighted (attention) sampling;
        # use ops.weighted.csr_weights_from_eid for COO-ordered weights.
        # CPU mode draws through the native engine's weighted path
        # (qt_sample_layer_weighted) with the same row_cap truncation,
        # so host and device draws share one distribution. Length is
        # validated HERE: the native engine reads weights[slot] through
        # a raw pointer, so a short array would be an out-of-bounds
        # read, not a Python exception.
        if edge_weight is not None:
            e = int(csr_topo.edge_count)
            got = int(np.shape(edge_weight)[0])
            if got != e:
                raise ValueError(
                    f"edge_weight has {got} entries but the topology "
                    f"has {e} edges (weights are CSR-slot-aligned; use "
                    "ops.csr_weights_from_eid for COO-ordered weights)")
        self.edge_weight = edge_weight
        self._weight_np = None     # cached f32 copy for the CPU engine
        self._eid_np = None        # cached eid map for the CPU engine
        # sampling="rotation": ~3x faster device path (wide row fetches
        # per seed over a shuffled CSR copy instead of k scattered
        # loads); "window" costs the same fetches but draws exact i.i.d.
        # k-subsets of each seed's >=129-entry shuffled window (subset-
        # independent within an epoch, exact for deg <= window). Both
        # shuffle once at init; call reshuffle() at each epoch boundary
        # so draws stay marginally uniform.
        if sampling not in ("exact", "rotation", "window"):
            raise ValueError(f"unknown sampling method {sampling!r}")
        if sampling in ("rotation", "window") and mode == "CPU":
            sampling = "exact"   # the CPU engine has its own sampler
        # weighted + rotation/window = the windowed weighted draw
        # (sample_layer_weighted_window): weight-exact for deg <= 129,
        # in-window renormalization bias on hubs (see its docstring) —
        # an explicit caller choice, not a silent fallback
        if sampling in ("rotation", "window") and \
                max(sizes, default=0) > 128:
            raise ValueError(
                f"{sampling} sampling supports fanouts <= 128")
        # with_eid: stamp every sampled edge with its global edge id
        # (CSRTopo.eid -> original COO position; CSR slot if no eid map),
        # delivered in Adj.e_id. Costs one scattered gather per edge, so
        # it is opt-in. CPU mode: the native engine emits each pick's
        # CSR slot (qt_sample_layer* out_slots), mapped through
        # CSRTopo.eid the same way.
        self.with_eid = with_eid
        self.sampling = sampling
        # layout="overlap": rotation/window do ONE 256-wide row gather
        # per seed instead of two 128-wide (fastest measured config,
        # docs/introduction.md) at 2x index memory. shuffle="butterfly":
        # the ~40x cheaper epoch reshuffle (masked swap network composed
        # across epochs) instead of the exact per-epoch sort.
        if layout not in ("pair", "overlap"):
            raise ValueError(f"unknown rotation layout {layout!r}")
        if shuffle not in ("sort", "butterfly"):
            raise ValueError(f"unknown shuffle {shuffle!r}")
        if shuffle == "butterfly" and edge_weight is not None and \
                sampling in ("rotation", "window"):
            # the WEIGHTED windowed draw anchors its window at the
            # segment start and relies on the reshuffle to re-place hub
            # neighbors uniformly; butterfly moves an element <= 255
            # positions per epoch, so a hub's far neighbors would stay
            # unreachable for many epochs — silent sampling bias.
            # (Unweighted rotation AND window are safe: both walk the
            # whole segment with a random per-draw anchor.)
            raise ValueError(
                "shuffle='butterfly' cannot provide the weighted "
                "windowed draw's mandatory hub re-placement (bounded "
                "per-epoch displacement; it anchors at the segment "
                "start) — use shuffle='sort' for weighted "
                "rotation/window")
        self.layout = layout
        self.shuffle = shuffle
        # HOST-mode placement on backends without pinned_host memory:
        # True = loud logged fallback to default placement, False = raise
        self.allow_fallback = allow_fallback
        # wide_exact: exact mode's wide-fetch path needs a layout view of
        # the indices — +E (pair) or +2E (overlap) memory in the
        # topology's tier. False keeps the zero-extra-copy scattered draw
        # (same statistics, k scattered loads per seed) for graphs whose
        # indices already fill most of HBM.
        self.wide_exact = wide_exact
        # collect_metrics: the jitted sample program also emits the
        # metrics.NUM_COUNTERS device counter vector (frontier fill vs
        # the static cap); sample() stashes it on ``self.last_counters``
        # — a device array, read lazily (StepStats.add_counters) so
        # sampling stays sync-free. CPU mode has no jitted program and
        # leaves last_counters as None.
        self.collect_metrics = bool(collect_metrics)
        self.last_counters = None
        self._key = jax.random.key(seed)
        self._placed = None
        self._weight_placed = None
        self._rot = None          # shuffled row view (pair or overlap)
        self._exact_rows = None   # un-shuffled row view (wide exact path)
        self._rot_w = None        # co-shuffled weight row view
        self._rot_eid = None      # slot->edge-id map in permuted coords
        self._permuted = None     # flat permuted indices (butterfly state)
        self._permuted_w = None   # flat co-permuted weights (butterfly)
        self._row_ids = None
        self._fns = {}

    # -- placement ----------------------------------------------------------
    def lazy_init_quiver(self):
        if self._placed is not None:
            return
        if self.mode == "CPU":
            self._placed = (np.asarray(self.csr_topo.indptr),
                            np.asarray(self.csr_topo.indices))
            return
        if getattr(self.csr_topo, "requires_host_sampling", lambda: False)():
            raise ValueError(
                "topology offsets exceed int32 in 32-bit jax mode; device "
                "sampling would silently wrap them — use mode='CPU' (the "
                "native host engine handles int64 offsets) or enable "
                "jax_enable_x64")
        dev = self.device
        if dev is None or isinstance(dev, int):
            platforms = [d for d in jax.devices()]
            dev = platforms[self.device or 0]
        if self.mode == "HOST":
            # host-resident topology (UVA analogue): keep arrays in host
            # memory; XLA streams them to device per sample step
            got = _pinned_put(
                [self.csr_topo.indptr, self.csr_topo.indices], dev,
                self.allow_fallback, "the topology")
            placed = (tuple(got) if got is not None else
                      (np.asarray(self.csr_topo.indptr),
                       np.asarray(self.csr_topo.indices)))
        else:
            placed = (jax.device_put(self.csr_topo.indptr, dev),
                      jax.device_put(self.csr_topo.indices, dev))
        self._placed = placed

    def _ensure_weights_placed(self):
        """Materialize the edge-weight array once — pinned host in HOST
        mode (E-sized arrays don't fit HBM there; same placement as the
        indices). The single entry point for sample() AND reshuffle(),
        whichever runs first."""
        if self._weight_placed is not None:
            return
        self._weight_placed = jnp.asarray(self.edge_weight)
        if self.mode == "HOST":
            got = _pinned_put([self._weight_placed],
                              list(self._weight_placed.devices())[0],
                              self.allow_fallback, "the edge weights")
            if got is not None:
                self._weight_placed = got[0]

    @staticmethod
    def _rows_np(flat, width=128, overlap=False):
        """numpy twin of ops.as_index_rows(_overlapping) — same layout
        formulas (asserted equal in tests) built WITHOUT touching device
        memory, for HOST mode where the E/2E view must never transit
        HBM."""
        e = flat.shape[0]
        nrows = (e + 2 * width - 1) // width + 1
        pad = nrows * width - e
        base = np.concatenate(
            [flat, np.zeros((pad,), flat.dtype)]).reshape(nrows, width)
        if not overlap:
            return base
        nxt = np.concatenate([base[1:], np.zeros_like(base[:1])])
        return np.concatenate([base, nxt], axis=1)

    def _ensure_exact_rows(self):
        """Layout view (pair/overlap per ``self.layout``) of the placed,
        UN-shuffled indices — the wide-fetch exact path's input. Built
        once. HOST mode builds it host-side (numpy) and pins it WITHOUT
        ever committing the E/2E array to device HBM — the mode exists
        because the topology doesn't fit there."""
        if self._exact_rows is not None:
            return self._exact_rows
        if self.mode == "HOST":
            rows_np = self._rows_np(np.asarray(self._placed[1]),
                                    overlap=self.layout == "overlap")
            dev = self.device
            if dev is None or isinstance(dev, int):
                dev = jax.devices()[self.device or 0]
            got = _pinned_put([rows_np], dev, self.allow_fallback,
                              "the exact rows view")
            # fallback: commit ONCE to default placement — caching raw
            # numpy would re-transfer the E/2E view every sample()
            rows = got[0] if got is not None else jnp.asarray(rows_np)
        else:
            from ..ops.sample import (as_index_rows,
                                      as_index_rows_overlapping)
            as_rows = (as_index_rows_overlapping
                       if self.layout == "overlap" else as_index_rows)
            rows = as_rows(jnp.asarray(self._placed[1]))
        self._exact_rows = rows
        return rows

    def reshuffle(self, key=None):
        """Re-shuffle every CSR row's neighbor order (rotation sampling's
        freshness source). Called automatically on first sample; call at
        each epoch boundary thereafter. shuffle="sort": exact uniform
        per-row shuffle (one 2-key sort over the edge array, ~650ms per
        100M edges). shuffle="butterfly": the ~40x cheaper masked swap
        network, composed across calls (this method keeps the running
        permuted state and the composed edge-id map for you)."""
        from ..ops.sample import (as_index_rows, as_index_rows_overlapping,
                                  butterfly_shuffle, edge_row_ids,
                                  permute_csr)
        self.lazy_init_quiver()
        indptr, indices = self._placed
        indptr = jnp.asarray(indptr)
        indices = jnp.asarray(indices)
        if self._row_ids is None:
            self._row_ids = jax.jit(edge_row_ids, static_argnums=1)(
                indptr, int(indices.shape[0]))
        pkey = key if key is not None else self.next_key()
        base = self.csr_topo.eid if self.with_eid else None
        weighted = self.edge_weight is not None
        bfly = self.shuffle == "butterfly"
        if weighted:
            self._ensure_weights_placed()
        if bfly:
            # composed state: feed the previous epoch's outputs back in
            src = self._permuted if self._permuted is not None else indices
            wsrc = (self._permuted_w if self._permuted_w is not None
                    else self._weight_placed)
        else:
            src, wsrc = indices, self._weight_placed
        extra = (wsrc,) if weighted else None
        fn = butterfly_shuffle if bfly else permute_csr
        out = fn(src, self._row_ids, pkey, with_slot_map=self.with_eid,
                 extra=extra)
        wp = None
        if self.with_eid and weighted:
            permuted, (wp,), smap = out
        elif self.with_eid:
            permuted, smap = out
        elif weighted:
            permuted, (wp,) = out
        else:
            permuted = out
        if self.with_eid:
            from ..ops.sample import compose_slot_map
            self._rot_eid = compose_slot_map(self._rot_eid, smap, base,
                                             bfly)
        if bfly:
            # (in HOST mode these are re-placed on pinned host in the
            # placement block below, AFTER the rows views are built —
            # pinning first would bounce E-sized arrays
            # host->device->host once per epoch)
            self._permuted = permuted
            self._permuted_w = wp
        as_rows = (as_index_rows_overlapping if self.layout == "overlap"
                   else as_index_rows)
        rows = as_rows(permuted)
        self._rot_w = as_rows(wp) if weighted else None
        if self.mode == "HOST":
            # keep the shuffled topology host-resident (the mode exists
            # because indices don't fit HBM); the sampler's row fetches
            # then stream from host like the exact path's. The E-sized
            # edge-id map and the butterfly's persistent permuted state
            # get the same placement for the same reason.
            arrays = [rows, self._rot_w, self._rot_eid, self._permuted,
                      self._permuted_w]
            got = _pinned_put([a for a in arrays if a is not None],
                              list(rows.devices())[0],
                              self.allow_fallback, "the shuffled rows")
            if got is not None:
                it = iter(got)
                (rows, self._rot_w, self._rot_eid, self._permuted,
                 self._permuted_w) = [
                    next(it) if a is not None else None for a in arrays]
        self._rot = rows

    def _exact_hub_frac(self):
        """Static hub fraction sizing the wide-exact scattered-load
        budget — the degree-bucket split computed once per graph and
        cached on the topology (CSRTopo.exact_bucket_meta); None when
        the wide-fetch exact path is not in play."""
        if self.sampling != "exact" or not self.wide_exact \
                or self.edge_weight is not None or self.mode == "CPU":
            return None
        return float(self.csr_topo.exact_bucket_meta(step=128).frac)

    # -- core ---------------------------------------------------------------
    def _build_fn(self, batch_size: int):
        sizes = self.sizes
        weighted = self.edge_weight is not None
        method = self.sampling
        hub_frac = self._exact_hub_frac()
        eid_mode = "none"
        if self.with_eid:
            # rotation/window always need the co-permuted map; otherwise
            # the topo's eid map if present, else raw CSR slots
            eid_mode = ("map" if (method in ("rotation", "window")
                                  or self.csr_topo.eid is not None)
                        else "slots")

        stride = 128 if self.layout == "overlap" else None
        collect = self.collect_metrics

        def run(indptr, indices, seeds, key, weights=None, rows=None,
                eid_arr=None, w_rows=None):
            from ..ops.sample_multihop import sample_multihop
            eid = {"none": None, "slots": True, "map": eid_arr}[eid_mode]
            col = None
            if collect:
                from ..metrics import Collector
                col = Collector()
            out = sample_multihop(indptr, indices, seeds, sizes, key,
                                  edge_weight=weights if weighted else None,
                                  method=method, indices_rows=rows,
                                  eid=eid,
                                  indices_stride=stride if rows is not None
                                  else None,
                                  weight_rows=w_rows, hub_frac=hub_frac,
                                  collector=col)
            if collect:
                return out + (col.counters(),)
            return out

        return jax.jit(run)

    def _fn_for(self, batch_size: int):
        # keyed on collect_metrics too: the jitted fn's output arity is
        # baked in at build time, so toggling the knob must not reuse a
        # cached fn with the other arity
        key = (batch_size, bool(self.collect_metrics))
        fn = self._fns.get(key)
        if fn is None:
            fn = self._build_fn(batch_size)
            self._fns[key] = fn
        return fn

    def next_key(self):
        self._key, sub = jax.random.split(self._key)
        return sub

    def sample(self, input_nodes):
        """Returns (n_id, batch_size, adjs) — adjs ordered outermost hop
        first, ready for layer-wise message passing (PyG convention)."""
        self.lazy_init_quiver()
        seeds = jnp.asarray(input_nodes, dtype=jnp.int32)
        bs = int(seeds.shape[0])
        indptr, indices = self._placed
        if self.mode == "CPU":
            return self._sample_cpu(seeds, bs)
        fn = self._fn_for(bs)
        if self.edge_weight is not None:
            self._ensure_weights_placed()
        if self.sampling in ("rotation", "window"):
            if self._rot is None:
                self.reshuffle()
            rows = self._rot
            w_rows = self._rot_w
            eid_arr = self._rot_eid
        else:
            # exact mode: the wide-fetch path wants a layout view of the
            # SAME un-shuffled indices (no reshuffle needed — Fisher-
            # Yates positions are uniform under any fixed order); the
            # weighted pool draw has no use for it
            rows = (self._ensure_exact_rows()
                    if self.edge_weight is None and self.wide_exact
                    else None)
            w_rows = None
            eid_arr = (jnp.asarray(self.csr_topo.eid)
                       if self.with_eid and self.csr_topo.eid is not None
                       else None)
        out = fn(jnp.asarray(indptr), jnp.asarray(indices),
                 seeds, self.next_key(), self._weight_placed, rows,
                 eid_arr, w_rows)
        if self.collect_metrics:
            n_id, layers, self.last_counters = out
        else:
            n_id, layers = out
        shapes = layer_shapes(bs, self.sizes)
        adjs = []
        for layer, shape in zip(layers, shapes):
            edge_index = jnp.stack([layer.col, layer.row])
            adjs.append(Adj(edge_index=edge_index,
                            e_id=layer.e_id,
                            size=(shape.n_id_cap, shape.num_seeds),
                            mask=layer.col >= 0))
        return n_id, bs, adjs[::-1]

    def _sample_cpu(self, seeds, bs):
        from ..native import cpu_sample_multihop
        indptr, indices = self._placed
        if self.edge_weight is not None and self._weight_np is None:
            # one-time f32 contiguous copy (an E-sized memcpy per batch
            # would dwarf the sampling work on big graphs)
            self._weight_np = np.ascontiguousarray(self.edge_weight,
                                                   dtype=np.float32)
        w = self._weight_np
        out = cpu_sample_multihop(
            indptr, indices, np.asarray(seeds), self.sizes,
            seed=int(jax.random.randint(self.next_key(), (), 0, 2 ** 31 - 1)),
            weights=w, with_slots=self.with_eid)
        if self.with_eid:
            n_id, rows, cols, slot_lists = out
            if self._eid_np is None and self.csr_topo.eid is not None:
                # one-time host copy (E-sized D2H per batch would dwarf
                # the sampling work, like _weight_np above)
                self._eid_np = np.asarray(self.csr_topo.eid)
            eid_map = self._eid_np
        else:
            n_id, rows, cols = out
            slot_lists = [None] * len(rows)
        shapes = layer_shapes(bs, self.sizes)
        adjs = []
        for (row, col, slots), shape in zip(zip(rows, cols, slot_lists),
                                            shapes):
            edge_index = jnp.asarray(np.stack([col, row]))
            e_id = None
            if slots is not None:
                e = (slots if eid_map is None
                     else np.where(slots >= 0,
                                   eid_map[np.clip(slots, 0, None)], -1))
                e_id = jnp.asarray(e)
            adjs.append(Adj(edge_index=edge_index,
                            e_id=e_id,
                            size=(shape.n_id_cap, shape.num_seeds),
                            mask=edge_index[0] >= 0))
        return jnp.asarray(n_id), bs, adjs[::-1]

    # -- aux ----------------------------------------------------------------
    def sample_layer(self, batch, size):
        self.lazy_init_quiver()
        indptr, indices = self._placed
        seeds = jnp.asarray(batch, jnp.int32)
        return sample_layer(jnp.asarray(indptr), jnp.asarray(indices),
                            seeds, size, self.next_key())

    def reindex(self, inputs, outputs, counts=None):
        return compact_layer(jnp.asarray(inputs, jnp.int32),
                             jnp.asarray(outputs, jnp.int32))

    def sample_prob(self, train_idx, total_node_count):
        self.lazy_init_quiver()
        if self.mode == "CPU":
            indptr = jnp.asarray(self._placed[0])
            indices = jnp.asarray(self._placed[1])
        else:
            indptr, indices = self._placed
        return sample_prob(jnp.asarray(indptr), jnp.asarray(indices),
                           jnp.asarray(train_idx), self.sizes,
                           total_node_count)

    # -- process sharing (API compat; jax is single-process-per-host) -------
    def share_ipc(self):
        return (self.csr_topo, self.device, self.mode, self.sizes,
                self.edge_weight, self.sampling, self.with_eid,
                self.layout, self.shuffle, self.wide_exact,
                self.allow_fallback)

    @classmethod
    def lazy_from_ipc_handle(cls, ipc_handle):
        # older short handles (7-tuple: no layout/shuffle; 9-tuple: no
        # wide_exact/allow_fallback) still load and get the ctor
        # defaults, like the Mixed sampler's handle[:6] pattern
        (csr_topo, device, mode, sizes, edge_weight, sampling,
         with_eid) = ipc_handle[:7]
        extras = {}
        for pos, name in ((7, "layout"), (8, "shuffle"),
                          (9, "wide_exact"), (10, "allow_fallback")):
            if len(ipc_handle) > pos:
                extras[name] = ipc_handle[pos]
        return cls(csr_topo, sizes, device=device, mode=mode,
                   edge_weight=edge_weight, sampling=sampling,
                   with_eid=with_eid, **extras)


class SampleJob(Generic[T_co]):
    """Abstract shuffled task source for the mixed sampler
    (reference: sage_sampler.py:180-195)."""

    def __getitem__(self, index) -> T_co:
        raise NotImplementedError

    def __len__(self) -> int:
        raise NotImplementedError

    def shuffle(self) -> None:
        raise NotImplementedError


class MixedGraphSageSampler:
    """Hybrid device+host sampling scheduler.

    Keeps the reference's adaptive work-splitting idea
    (sage_sampler.py:207-368): measure device vs host per-task sample time
    and hand the host a proportional quota each round. The host path uses
    the native C++ sampler (``quiver_tpu.native``) on a thread pool —
    threads, not daemon processes, because the GIL is released inside the
    native call and one process owns the TPU.
    """

    #: EMA smoothing for per-task time estimates (higher = faster adapt)
    EMA_ALPHA = 0.25

    def __init__(self, sample_job: SampleJob, sizes: Sequence[int],
                 csr_topo: CSRTopo, device=None,
                 device_mode: str = "HBM", num_workers: int = 2,
                 seed: int = 0, **device_sampler_kwargs):
        self.job = sample_job
        self.sizes = list(sizes)
        self.num_workers = max(1, num_workers)
        # device_sampler_kwargs pass through to the DEVICE side
        # (sampling="rotation", layout=, shuffle=). edge_weight and
        # with_eid ALSO reach the host side: the native engine's
        # weighted path draws with the device pool draw's contract (k
        # with-replacement picks ~ weight, row_cap truncation) and its
        # samplers emit per-pick CSR slots mapped through CSRTopo.eid —
        # so batches from either engine share one distribution and one
        # e_id semantics regardless of timing-dependent provenance.
        if device_sampler_kwargs.get("edge_weight") is not None and \
                device_sampler_kwargs.get("sampling", "exact") != "exact":
            raise ValueError(
                "mixed weighted sampling pins sampling='exact': the "
                "host engine mirrors the exact weighted pool draw, and "
                "the weighted windowed draw (rotation/window) is a "
                "different distribution — batches would skew depending "
                "on which engine produced them")
        self._device_kwargs = dict(device_sampler_kwargs)
        self.device_sampler = GraphSageSampler(
            csr_topo, sizes, device=device, mode=device_mode, seed=seed,
            **device_sampler_kwargs)
        self.cpu_sampler = GraphSageSampler(
            csr_topo, sizes, mode="CPU", seed=seed + 1,
            edge_weight=device_sampler_kwargs.get("edge_weight"),
            with_eid=bool(device_sampler_kwargs.get("with_eid", False)))
        self._pool = None
        self._device_time = None       # EMA seconds per device task
        self._cpu_time = None          # EMA seconds per host task
        import threading
        self._time_lock = threading.Lock()   # _cpu_one runs on pool threads

    def _ensure_pool(self):
        if self._pool is None:
            import concurrent.futures
            import weakref
            self._pool = concurrent.futures.ThreadPoolExecutor(
                max_workers=self.num_workers)
            # lifecycle: host-sampling threads must not outlive the
            # sampler across long runs — explicit close() below, with a
            # GC finalizer safety net (bound to the pool, not self)
            self._pool_finalizer = weakref.finalize(
                self, self._pool.shutdown, wait=False)

    def close(self):
        """Shut down the host-sampling worker pool (idempotent); safe
        to call between epochs — the next iteration re-creates it."""
        pool, self._pool = self._pool, None
        if pool is not None:
            fin = getattr(self, "_pool_finalizer", None)
            if fin is not None:
                fin.detach()
            pool.shutdown(wait=True, cancel_futures=True)

    def _ema(self, old, dt):
        a = self.EMA_ALPHA
        return dt if old is None else a * dt + (1.0 - a) * old

    def decide_task_num(self):
        device_tasks = max(20, 2 * self.num_workers)
        if not self._device_time or not self._cpu_time:
            return device_tasks, self.num_workers
        ratio = self._cpu_time / max(self._device_time, 1e-9)
        cpu_tasks = min(
            int(device_tasks / max(ratio / self.num_workers, 1e-9)),
            device_tasks * self.num_workers)
        return device_tasks, max(0, cpu_tasks)

    def __iter__(self):
        self.job.shuffle()
        if getattr(self.device_sampler, "sampling", "exact") in (
                "rotation", "window") and \
                getattr(self.device_sampler, "_rot", None) is not None:
            # epoch boundary: the mixed layer knows it (it just
            # reshuffled the job), so it owns the rotation refresh too
            # rather than pushing sampler internals onto callers
            self.device_sampler.reshuffle()
        self._ensure_pool()
        import concurrent.futures as cf
        n = len(self.job)
        idx = 0
        pending: List = []

        def drain_done():
            nonlocal pending
            done = [f for f in pending if f.done()]
            pending = [f for f in pending if not f.done()]
            return done

        while idx < n or pending:
            device_quota, cpu_quota = self.decide_task_num()

            def dispatch_host():
                # keep the pool fed up to its width, within this round's
                # quota; never queue past the width — tasks queued beyond
                # it are pure backlog, and during bootstrap (no host
                # measurement yet) an unbounded queue would commit dozens
                # of batches to a host pool that may turn out to be
                # 1000x slower than the device
                nonlocal idx, cpu_quota
                while (idx < n and cpu_quota > 0
                       and len(pending) < self.num_workers):
                    seeds = self.job[idx]
                    idx += 1
                    cpu_quota -= 1
                    pending.append(self._pool.submit(
                        self._cpu_one, np.asarray(seeds)))

            dispatch_host()
            # run device tasks inline, yielding finished host tasks
            # between them (non-blocking — the reference's round barrier
            # would stall the device on the slowest host task) and
            # refilling the host pool as slots free up
            for _ in range(device_quota):
                if idx >= n:
                    break
                seeds = self.job[idx]
                idx += 1
                t0 = time.perf_counter()
                out = self.device_sampler.sample(seeds)
                jax.block_until_ready(out[0])
                self._device_time = self._ema(
                    self._device_time, time.perf_counter() - t0)
                yield out
                for fut in drain_done():
                    yield fut.result()
                dispatch_host()
            for fut in drain_done():
                yield fut.result()
            if idx >= n and pending:
                # everything dispatched: now blocking is idle-waiting,
                # not stalling — take tasks as they finish
                done, rest = cf.wait(pending,
                                     return_when=cf.FIRST_COMPLETED)
                pending = list(rest)
                for fut in done:
                    yield fut.result()

    def _cpu_one(self, seeds):
        t0 = time.perf_counter()
        out = self.cpu_sampler.sample(seeds)
        dt = time.perf_counter() - t0
        with self._time_lock:          # concurrent pool threads
            self._cpu_time = self._ema(self._cpu_time, dt)
        return out

    def share_ipc(self):
        return (self.job, self.sizes, self.device_sampler.csr_topo,
                self.device_sampler.device, self.device_sampler.mode,
                self.num_workers, self._device_kwargs)

    @classmethod
    def lazy_from_ipc_handle(cls, handle):
        # older 6-tuple handles (no device kwargs) still load
        job, sizes, csr_topo, device, mode, workers = handle[:6]
        kwargs = handle[6] if len(handle) > 6 else {}
        return cls(job, sizes, csr_topo, device=device,
                   device_mode=mode, num_workers=workers, **kwargs)
