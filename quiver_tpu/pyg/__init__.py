from .sage_sampler import (
    Adj,
    GraphSageSampler,
    MixedGraphSageSampler,
    SampleJob,
)

__all__ = ["Adj", "GraphSageSampler", "MixedGraphSageSampler", "SampleJob"]
