"""Checkpoint / resume.

The reference has no in-library checkpointing (survey §5: example-level
pytorch-lightning only). Here it is first-class: orbax-backed save/
restore of the fused TrainState plus numpy artifacts for preprocessing
products (partitions, cache orders) — the equivalents of the
``torch.save`` artifact files (partition.py:133-141, preprocess.py).
"""

from __future__ import annotations

import os
from typing import Any, Optional

import jax
import numpy as np


def _ocp():
    import orbax.checkpoint as ocp
    return ocp


def save_state(path: str, state: Any, step: Optional[int] = None,
               force: bool = True):
    """Save a pytree (e.g. ``parallel.train.TrainState``) with orbax."""
    ocp = _ocp()
    path = os.path.abspath(path)
    with ocp.StandardCheckpointer() as ckptr:
        target = os.path.join(path, str(step)) if step is not None else path
        ckptr.save(target, state, force=force)
    return path


def restore_state(path: str, example: Any, step: Optional[int] = None):
    """Restore a pytree saved by ``save_state``; ``example`` supplies the
    structure/shapes/dtypes."""
    ocp = _ocp()
    path = os.path.abspath(path)
    target = os.path.join(path, str(step)) if step is not None else path
    with ocp.StandardCheckpointer() as ckptr:
        return ckptr.restore(
            target, jax.tree.map(ocp.utils.to_shape_dtype_struct, example))


def save_artifact(path: str, **arrays):
    """Preprocessing artifacts (partition books, cache orders, hot
    permutations) as a single .npz."""
    os.makedirs(os.path.dirname(os.path.abspath(path)) or ".", exist_ok=True)
    np.savez(path, **{k: np.asarray(v) for k, v in arrays.items()})
    return path


def load_artifact(path: str) -> dict:
    with np.load(path, allow_pickle=False) as z:
        return {k: z[k] for k in z.files}
