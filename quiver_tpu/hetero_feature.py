"""Per-node-type tiered feature stores for heterogeneous graphs.

The reference's MAG240M path pairs its sampler with a partitioned /
disk-tier feature pipeline (benchmarks/ogbn-mag240m/preprocess.py,
train_quiver_multi_node.py:21-23) — but only for the homogeneous
paper-feature matrix. ``HeteroFeature`` extends the flagship ``Feature``
machinery (HBM cache tiers, replicate/shard policies over the mesh,
numpy/offload host tiers, mmap disk tier, hot-order reindexing,
prefetch double-buffering) across node TYPES: each type gets its own
``Feature`` store with its own budget/policy/dtype, so a MAG240M-shaped
config puts the 100M-row paper matrix in the host (or disk) tier with a
small HBM cache while the author/institution matrices sit fully in HBM.

``lookup(frontier)`` consumes the hetero sampler's per-type frontier
dicts directly, honoring the -1 mask convention (masked rows are
zeroed, matching the hand-rolled gather the R-GCN example used before).
"""

from __future__ import annotations

from typing import Dict, Optional

import jax.numpy as jnp
import numpy as np

from .feature import Feature


class HeteroFeature:
    """``{node_type: Feature}`` with a frontier-shaped lookup.

    Build via :meth:`from_cpu_tensors`; per-type construction knobs come
    from ``configs[node_type]`` overlaid on ``default`` (both plain
    kwarg dicts for :class:`Feature` — ``device_cache_size``,
    ``cache_policy``, ``csr_topo``, ``mesh``, ``dtype``,
    ``host_placement``, ``cold_budget``, ``dedup_cold``,
    ``dtype_policy``...). Hetero frontiers repeat hub nodes across
    relations, so ``default={"dedup_cold": True}`` bounds every type's
    host-tier traffic by its unique cold nodes — and because the knobs
    are per type, a MAG240M-shaped config can store the 100M-row paper
    matrix int8 (quarter the host bytes, fused dequant) while the
    small author/institution matrices stay fp32 in HBM:
    ``configs={"paper": {"dtype_policy": "int8"}}``.
    """

    def __init__(self, stores: Dict[str, Feature]):
        self.stores = dict(stores)
        self._pool = None

    @classmethod
    def from_cpu_tensors(cls, feats: Dict[str, np.ndarray],
                         configs: Optional[Dict[str, dict]] = None,
                         default: Optional[dict] = None) -> "HeteroFeature":
        configs = configs or {}
        default = default or {}
        unknown = set(configs) - set(feats)
        if unknown:
            raise ValueError(
                f"configs for unknown node type(s) {sorted(unknown)}; "
                f"have {sorted(feats)}")
        stores = {}
        for t, arr in feats.items():
            kw = dict(default)
            kw.update(configs.get(t, {}))
            stores[t] = Feature(**kw).from_cpu_tensor(arr)
        return cls(stores)

    @property
    def node_types(self):
        return list(self.stores.keys())

    def __getitem__(self, node_type: str) -> Feature:
        return self.stores[node_type]

    def _lookup_one(self, node_type: str, ids):
        # Feature fuses the clip+gather+mask into one dispatch on the
        # pure-HBM path — per-type dispatch latency matters behind a
        # tunnel (see feature.py _build_gather)
        return self.stores[node_type].getitem_masked(ids)

    def lookup(self, frontier: Dict[str, object]) -> Dict[str, object]:
        """Gather features for a hetero frontier dict (``None`` entries
        skipped, -1-masked ids produce zero rows)."""
        return {t: self._lookup_one(t, ids)
                for t, ids in frontier.items() if ids is not None}

    def prefetch(self, frontier: Dict[str, object]):
        """Start ``lookup(frontier)`` on the staging pipeline; returns
        a ``Future`` whose ``result()`` equals the lookup. Same
        double-buffering story as ``Feature.prefetch``: the host-tier
        staging of batch i+1 overlaps batch i's model step. Bounded,
        ordered, shut down by :meth:`close` (or at GC)."""
        if self._pool is None:
            from .pipeline import Pipeline
            self._pool = Pipeline(depth=2, name="quiver-hetero-prefetch")
        snap = {t: (None if ids is None else jnp.asarray(ids))
                for t, ids in frontier.items()}
        return self._pool.submit(self.lookup, snap)

    def close(self):
        """Shut down the prefetch pipeline and every per-type store's
        (idempotent)."""
        pool, self._pool = self._pool, None
        if pool is not None:
            pool.close()
        for store in self.stores.values():
            store.close()

    def size(self, node_type: str, dim: int) -> int:
        return self.stores[node_type].size(dim)

    def __getstate__(self):
        state = dict(self.__dict__)
        state["_pool"] = None
        return state
