"""The capacity model: "a fleet of N replicas sustains X req/s of mix
M within SLO" — derived, emitted, and REPLAY-VERIFIED (qt-capacity).

The model composes three evidence sources the stack already produces:

- the **analytic cost model** (``analysis.costmodel.CostModel`` — the
  serve step's minimum byte traffic) divided by the **roofline probe**
  (``profile.machine_probe`` — this box's achieved gather GB/s) gives
  a service-time FLOOR no measurement may undercut;
- an **observed** per-batch dispatch time (a timed ``ServeEngine.run``
  loop, or :func:`observe_serving` folding live ``serving`` JSONL)
  gives the device service time; the coalescer's per-request host cost
  (``overhead_per_req_ms`` — queue hop, slot bookkeeping, future
  delivery; calibrated from a serial round-trip) runs CONCURRENTLY
  with dispatch when ``pipeline_depth >= 2``, so the batch cycle time
  is ``s = max(dispatch, fill · overhead)`` — whichever side of the
  pipeline is the bottleneck;
- the serving layer's queueing discipline (coalesce up to
  ``max_wait``, dispatch, p99 budget) bounds how hot the pipeline may
  run: with latency headroom ``w = budget_p99 - s - max_wait``, the
  utilization cap is ``ρ* = 2w / (2w + s)`` — the M/D/1 mean-wait
  bound (wait grows like ``s·ρ/(2(1-ρ))``, held under ``w``), clipped
  to [0.05, 0.95]. The bound deliberately carries no extra tail
  margin: the offered load this prediction is verified against is
  *paced* — ``traffic.generate_scenario``'s stratified arrivals are
  near-deterministic by construction (the price of chunk-invariant
  traces), and a rate-limited production upstream looks the same —
  so queueing stays mild until utilization approaches the clip
  ceiling; an open-loop Poisson storm would need the fatter tail
  margin this formula once carried (the replay gate caught the 3x
  version under-predicting the latency-bound regime ~2x). It is a
  HEURISTIC and documented as such; the honest part is that
  ``benchmarks/bench_capacity.py`` replays the predicted mix at the
  predicted rate and gates on the prediction landing within tolerance
  of the measured sustained rate (:func:`verdict`).

Throughput then follows from batch amortization: each replica ships
``fill`` requests per ``s``-long batch cycle, so ``predicted_rps =
replicas · fill · ρ* / s``, with ``fill`` the self-consistent fixed
point of the coalescer's fill law ``fill = clip(rate_per_replica ·
(max_wait + s), 1, batch_cap)`` (``s`` itself depends on ``fill``
through the overhead term, so the two iterate jointly).

Everything here is host-side arithmetic — no jax import, mirroring
``rpc.py``/``traffic.py`` — and the result is one JSONL record (kind
``capacity``, via :func:`emit`) that ``scripts/qt_capacity.py``
renders and ``scripts/qt_top.py`` shows as the capacity line.
"""

from __future__ import annotations

from typing import Dict, Optional

__all__ = ["predict", "observe_serving", "verdict", "emit"]


def _total_bytes(cost) -> Optional[int]:
    """``CostModel`` | its ``record()`` dict | plain int -> bytes."""
    if cost is None:
        return None
    if isinstance(cost, (int, float)):
        return int(cost)
    if isinstance(cost, dict):
        v = cost.get("total_bytes")
        return None if v is None else int(v)
    v = getattr(cost, "total_bytes", None)
    return None if v is None else int(v)


def predict(*, batch_cap: int, dispatch_ms: float, budget_p99_ms: float,
            mix: Optional[Dict[str, float]] = None, replicas: int = 1,
            max_wait_ms: float = 2.0, fill: Optional[float] = None,
            overhead_per_req_ms: float = 0.0,
            probe: Optional[dict] = None, cost=None) -> dict:
    """The capacity prediction record (see module docstring for the
    model).

    ``dispatch_ms`` is the observed full-fill batch service time;
    ``cost`` (a ``CostModel``, its ``record()`` dict, or total bytes)
    plus ``probe`` (a ``machine_probe()`` dict) floor it at the
    roofline — a dispatch measurement faster than the modeled minimum
    byte traffic at probed bandwidth is clock noise, not capacity.
    ``overhead_per_req_ms`` is the coalescer's per-request host cost
    (serial round-trip minus serial dispatch — the calibration
    ``benchmarks/bench_capacity.py`` runs); it bounds the cycle time
    from the host side of the pipeline. ``fill`` pins the per-batch
    fill instead of solving the fixed point. ``mix`` (tenant ->
    weight) splits the predicted rate into per-tenant shares."""
    if batch_cap < 1:
        raise ValueError(f"batch_cap must be >= 1, got {batch_cap}")
    if replicas < 1:
        raise ValueError(f"replicas must be >= 1, got {replicas}")
    if dispatch_ms <= 0:
        raise ValueError(f"dispatch_ms must be > 0, got {dispatch_ms}")
    if budget_p99_ms <= 0:
        raise ValueError(
            f"budget_p99_ms must be > 0, got {budget_p99_ms}")
    if overhead_per_req_ms < 0:
        raise ValueError(f"overhead_per_req_ms must be >= 0, got "
                         f"{overhead_per_req_ms}")
    floor_ms = None
    tb = _total_bytes(cost)
    if tb is not None and probe:
        gbps = float(probe.get("gather_gbps") or 0.0)
        if gbps > 0:
            floor_ms = tb / (gbps * 1e9) * 1e3
    service_ms = max(float(dispatch_ms), floor_ms or 0.0)

    def cycle_of(f):
        # pipeline_depth >= 2 overlaps device dispatch with host
        # coalescing: the batch cycle is whichever side is slower
        return max(service_ms, f * float(overhead_per_req_ms))

    def rho_of(cyc):
        # M/D/1 mean-wait bound for paced offered load (module
        # docstring) — no extra tail margin on purpose
        headroom_ms = budget_p99_ms - cyc - max_wait_ms
        r = 2.0 * headroom_ms / (2.0 * headroom_ms + cyc) \
            if headroom_ms > 0 else 0.0
        return min(max(r, 0.05), 0.95)

    if fill is None:
        # the coalescer's fill law, iterated to its fixed point: a
        # replica running at rate r fills batches with r·(max_wait+s)
        # arrivals (clipped to the seed block) — and the rate itself
        # is fill·ρ*/s, with s = cycle(fill). Monotone — but in the
        # latency-bound regime the decay toward the fill=1 floor is
        # geometric with ratio ρ*·(max_wait+s)/s, which approaches 1
        # as ρ* does, so the iteration budget must cover a slow crawl
        # (16 rounds once left it stranded at fill≈3, a 3x
        # over-prediction the replay gate caught).
        f = float(batch_cap)
        for _ in range(512):
            cyc = cycle_of(f)
            rho = rho_of(cyc)
            per_replica_rps = f * rho / (cyc / 1e3)
            f_new = min(max(per_replica_rps
                            * (max_wait_ms + cyc) / 1e3, 1.0),
                        float(batch_cap))
            if abs(f_new - f) < 1e-9:
                break
            f = f_new
        fill = f
    else:
        fill = min(max(float(fill), 1.0), float(batch_cap))
    cycle_ms = cycle_of(fill)
    rho = rho_of(cycle_ms)
    predicted = replicas * fill * rho / (cycle_ms / 1e3)

    rec = {
        "replicas": int(replicas),
        "batch_cap": int(batch_cap),
        "dispatch_ms": round(float(dispatch_ms), 6),
        "floor_ms": None if floor_ms is None else round(floor_ms, 6),
        "service_ms": round(service_ms, 6),
        "overhead_per_req_ms": round(float(overhead_per_req_ms), 6),
        "cycle_ms": round(cycle_ms, 6),
        "budget_p99_ms": round(float(budget_p99_ms), 6),
        "max_wait_ms": round(float(max_wait_ms), 6),
        "utilization_cap": round(rho, 6),
        "fill": round(float(fill), 4),
        "predicted_rps": round(predicted, 3),
    }
    if mix:
        if any(w <= 0 for w in mix.values()):
            raise ValueError(
                f"mix needs positive tenant weights, got {mix}")
        wsum = sum(mix.values())
        rec["mix"] = {t: round(w / wsum, 6)
                      for t, w in sorted(mix.items())}
        rec["per_tenant_rps"] = {
            t: round(predicted * w / wsum, 3)
            for t, w in sorted(mix.items())}
    return rec


def observe_serving(records) -> dict:
    """Fold a ``serving``-kind JSONL record list (newest wins) into
    the observed inputs :func:`predict` takes: ``{"dispatch_ms"`` (the
    per-batch wall p50), ``"fill"`` (mean batch fill),
    ``"max_wait_ms"``, ``"batch_cap"`` (the fill cap knob)``}`` —
    absent keys mean the stream never carried that fact."""
    out: dict = {}
    for rec in records:
        if rec.get("kind") not in (None, "serving"):
            continue
        wall = rec.get("wall")
        if isinstance(wall, dict) and wall.get("p50_ms"):
            out["dispatch_ms"] = float(wall["p50_ms"])
        sv = rec.get("serving")
        if isinstance(sv, dict):
            if sv.get("mean_batch_fill"):
                out["fill"] = float(sv["mean_batch_fill"])
            knobs = sv.get("knobs")
            if isinstance(knobs, dict):
                if knobs.get("max_wait_ms") is not None:
                    out["max_wait_ms"] = float(knobs["max_wait_ms"])
                if knobs.get("batch_fill_cap") is not None:
                    out["batch_cap"] = int(knobs["batch_fill_cap"])
    return out


def verdict(prediction: dict, measured_rps: float,
            tol: float = 0.25) -> dict:
    """Judge one prediction against a replay-measured sustained rate:
    ``ratio = predicted / measured``, within tolerance when ``|ratio -
    1| <= tol``. Returns the JSONL-ready verdict block
    ``benchmarks/bench_capacity.py`` gates on and ``qt_top`` renders."""
    if measured_rps <= 0:
        raise ValueError(
            f"measured_rps must be > 0, got {measured_rps}")
    pred = float(prediction["predicted_rps"])
    ratio = pred / float(measured_rps)
    return {
        "predicted_rps": round(pred, 3),
        "measured_rps": round(float(measured_rps), 3),
        "ratio": round(ratio, 4),
        "abs_err_frac": round(abs(ratio - 1.0), 4),
        "tol": float(tol),
        "within_tol": abs(ratio - 1.0) <= tol,
    }


def emit(sink, rec: dict) -> dict:
    """Append one capacity record (a :func:`predict` output, usually
    with a ``verdict`` block merged in) to a ``metrics.MetricsSink``
    as kind ``capacity``."""
    return sink.emit(rec, kind="capacity")
