"""RPC front end for the serving fleet: deadlines, retries, hedging.

ROADMAP frontier 4(a) names the shape — "an asyncio front end over
``MicroBatchServer.submit`` speaking a simple length-prefixed RPC, so
load generators and real clients hit it over a socket" — and this
module is that front end plus the CLIENT discipline a fleet needs to
survive its own replicas:

**Wire format** (both directions): a 4-byte big-endian unsigned length
prefix, then that many bytes of UTF-8 JSON. One logical message per
frame; a connection multiplexes many in-flight requests, correlated by
a client-chosen ``id``. Requests::

    {"op": "lookup", "id": 7, "node": 123,
     "budget_ms": 80.0,                  # remaining deadline budget
     "ctx": {"qt.trace_id": ..., ...}}   # optional tracing.inject()
    {"op": "ping", "id": 8}

Responses::

    {"id": 7, "ok": true, "row": [...]}            # float32 logits row
    {"id": 7, "ok": false, "error": "DeadlineExceeded",
     "message": "..."}
    {"id": 8, "ok": true, "pong": true, "health": 0.83}

**Deadlines are a budget, not a wall-clock timestamp** (fleet clocks
disagree): the client sends the milliseconds REMAINING at send time;
the server restarts the clock at arrival. A request whose budget is
already spent is shed immediately — before it wastes a coalescer batch
slot (:class:`~quiver_tpu.serving.MicroBatchServer` drops expired
requests at coalesce time too, via ``submit(deadline=...)``).

**The client** (:class:`RpcClient`) owns the failure discipline:

- *timeout → retry*: capped exponential backoff with FULL jitter
  (seeded ``random.Random`` — reproducible), each retry routed to the
  next-healthiest replica (:class:`~quiver_tpu.fleet.HealthRouter`
  when attached, seeded rotation otherwise); connection failures fail
  every in-flight request on that connection with
  :class:`ReplicaUnavailable` and the next attempt reconnects;
- *hedging*: when the primary attempt is still unanswered after the
  client's OBSERVED p95 latency (tracked per client, floor/ceiling
  clamped), the same request is re-issued to the next-healthiest
  replica; first answer wins and the loser is cancelled — safe because
  serve lookups are read-only/idempotent (a duplicate dispatch costs a
  batch slot, never a wrong answer);
- *typed failure, never silence*: every ``lookup`` resolves with a row
  or raises a typed :class:`RpcError` (``DeadlineExceeded``,
  ``Overloaded``, ``ServerClosed``, ``ReplicaUnavailable``, or
  :class:`AllAttemptsFailed` carrying the per-attempt causes). Zero
  accepted requests are silently lost — the chaos harness's
  acceptance bar.

Everything here is stdlib + numpy on HOST threads — no jax import, so
the fake-replica chaos harness loads this file through a synthetic
package in milliseconds, and nothing can enter a jitted program
(``qt_verify``'s invariants hold by construction).
"""

from __future__ import annotations

import asyncio
import collections
import json
import random
import struct
import threading
import time
from typing import Dict, List, Optional, Sequence, Tuple

import numpy as np

from . import faults, tracing

__all__ = ["RpcError", "DeadlineExceeded", "AttemptTimeout",
           "Overloaded", "ServerClosed", "ReplicaUnavailable",
           "AllAttemptsFailed", "RpcServer", "RpcClient", "read_frame",
           "write_frame", "MAX_FRAME"]

#: frame size bound: a length prefix claiming more than this is a
#: protocol error (garbage/hostile peer), not an allocation request
MAX_FRAME = 8 << 20

_LEN = struct.Struct(">I")


# -- typed errors (the wire's ``error`` field <-> these classes) --------------


class RpcError(RuntimeError):
    """Base of every typed RPC failure; ``error`` is the wire name."""

    error = "ServerError"


class DeadlineExceeded(RpcError):
    """The request's deadline budget was spent — at admission, in the
    coalescer, or waiting for the answer. Not retried (the budget is
    the caller's; there is nothing left to spend)."""

    error = "DeadlineExceeded"


class AttemptTimeout(RpcError):
    """ONE attempt went unanswered within the per-attempt timeout
    (client-local, never on the wire). Retriable — the overall deadline
    budget may still have room, and the retry goes elsewhere."""

    error = "AttemptTimeout"


class Overloaded(RpcError):
    """The replica shed the request at admission (its queue was full).
    Retriable — another replica may have capacity."""

    error = "Overloaded"


class ServerClosed(RpcError):
    """The replica is shutting down (or its coalescer died): the
    request was never dispatched. Retriable elsewhere."""

    error = "ServerClosed"


class ReplicaUnavailable(RpcError):
    """Transport-level failure: connect refused, connection reset,
    torn frame. The replica may be dead — retriable elsewhere."""

    error = "ReplicaUnavailable"


class AllAttemptsFailed(RpcError):
    """Every retry (and hedge) failed; ``causes`` carries the
    per-attempt exceptions in order."""

    error = "AllAttemptsFailed"

    def __init__(self, msg: str, causes: Sequence[BaseException] = ()):
        super().__init__(msg)
        self.causes = list(causes)


_WIRE_ERRORS = {c.error: c for c in
                (RpcError, DeadlineExceeded, Overloaded, ServerClosed,
                 ReplicaUnavailable, AllAttemptsFailed)}

#: retriable wire errors — the others mean spending more attempts
#: cannot change the outcome
_RETRIABLE = ("Overloaded", "ServerClosed", "ReplicaUnavailable",
              "ServerError", "AttemptTimeout")


def _wire_error_of(exc: BaseException) -> Tuple[str, str]:
    """(wire name, message) for an exception the backend raised."""
    if isinstance(exc, RpcError):
        return exc.error, str(exc)
    name = type(exc).__name__
    if name == "OverloadError":          # serving.OverloadError, by
        return "Overloaded", str(exc)    # name: no serving import here
    return "ServerError", f"{name}: {exc}"


# -- framing ------------------------------------------------------------------


async def read_frame(reader: asyncio.StreamReader) -> Optional[dict]:
    """One length-prefixed JSON frame, or None at clean EOF. A torn
    prefix/body or an oversized length raises ``ConnectionError``."""
    try:
        head = await reader.readexactly(_LEN.size)
    except asyncio.IncompleteReadError as e:
        if not e.partial:
            return None                  # clean EOF between frames
        raise ConnectionError("torn frame prefix") from None
    (n,) = _LEN.unpack(head)
    if n > MAX_FRAME:
        raise ConnectionError(f"frame length {n} exceeds {MAX_FRAME}")
    try:
        body = await reader.readexactly(n)
    except asyncio.IncompleteReadError:
        raise ConnectionError("torn frame body") from None
    try:
        return json.loads(body.decode())
    except ValueError:
        raise ConnectionError("frame is not valid JSON") from None


def write_frame(writer: asyncio.StreamWriter, msg: dict) -> None:
    """Queue one frame on ``writer`` (caller drains)."""
    body = json.dumps(msg).encode()
    writer.write(_LEN.pack(len(body)) + body)


# -- the server ---------------------------------------------------------------


class RpcServer:
    """Asyncio front end over one serve backend.

    ``backend`` is duck-typed: ``submit(node_id, context=None[,
    deadline=None][, tenant=None]) -> concurrent.futures.Future`` (the
    ``MicroBatchServer`` contract; ``deadline`` — an absolute
    ``time.perf_counter()`` instant — is passed when the signature
    takes it, so the coalescer can shed expired work before it costs a
    batch slot; ``tenant`` — a tenant-class name from the request's
    ``tenant`` wire field — likewise, so per-tenant SLO accounting and
    shed-order policy apply fleet-wide) plus optional ``health() ->
    {"score": float, ...}`` for ``ping``. The loop runs on a daemon thread; ``port=0`` binds
    ephemeral (read ``.port`` back). ``close()`` is idempotent.

    Each accepted request passes the ``rpc.request`` fault site —
    the chaos harness's replica kill/hang trigger."""

    def __init__(self, backend, host: str = "127.0.0.1", port: int = 0,
                 start: bool = True):
        self.backend = backend
        self.host = host
        self._want_port = int(port)
        self._loop: Optional[asyncio.AbstractEventLoop] = None
        self._server: Optional[asyncio.AbstractServer] = None
        self._thread: Optional[threading.Thread] = None
        self._ready = threading.Event()
        self._closed = False
        self.requests = 0
        self.shed_deadline = 0
        try:
            import inspect
            params = inspect.signature(backend.submit).parameters
            self._takes_deadline = "deadline" in params
            self._takes_tenant = "tenant" in params
        except (TypeError, ValueError):
            self._takes_deadline = False
            self._takes_tenant = False
        if start:
            self.start()

    # -- life cycle ----------------------------------------------------------
    def start(self) -> "RpcServer":
        if self._closed:
            raise ServerClosed("rpc server is closed")
        if self._thread is None:
            t = threading.Thread(target=self._run, name="qt-rpc-server",
                                 daemon=True)
            t.start()
            self._thread = t
            if not self._ready.wait(timeout=10.0):
                raise RuntimeError("rpc server failed to start")
        return self

    def _run(self) -> None:
        loop = asyncio.new_event_loop()
        self._loop = loop
        asyncio.set_event_loop(loop)

        async def boot():
            self._server = await asyncio.start_server(
                self._serve_conn, self.host, self._want_port)
            self._ready.set()

        loop.run_until_complete(boot())
        try:
            loop.run_forever()
        finally:
            to_cancel = asyncio.all_tasks(loop)
            for task in to_cancel:
                task.cancel()
            if to_cancel:
                loop.run_until_complete(asyncio.gather(
                    *to_cancel, return_exceptions=True))
            loop.close()

    @property
    def port(self) -> int:
        if self._server is None:
            return self._want_port
        return self._server.sockets[0].getsockname()[1]

    def close(self) -> None:
        """Stop accepting, cancel in-flight handlers, join the loop
        thread. Idempotent. The backend is NOT closed — the owner that
        built it closes it."""
        if self._closed:
            return
        self._closed = True
        loop, self._loop = self._loop, None
        t, self._thread = self._thread, None
        if loop is not None:
            def _stop():
                if self._server is not None:
                    self._server.close()
                loop.stop()
            try:
                loop.call_soon_threadsafe(_stop)
            except RuntimeError:
                pass                     # loop already gone
        if t is not None and t is not threading.current_thread():
            t.join(timeout=10.0)

    @property
    def closed(self) -> bool:
        return self._closed

    def __enter__(self) -> "RpcServer":
        return self

    def __exit__(self, *exc) -> None:
        self.close()

    # -- connection handling --------------------------------------------------
    async def _serve_conn(self, reader: asyncio.StreamReader,
                          writer: asyncio.StreamWriter) -> None:
        wlock = asyncio.Lock()
        tasks: set = set()
        try:
            while True:
                try:
                    msg = await read_frame(reader)
                except ConnectionError:
                    break                # hostile/torn peer: hang up
                if msg is None:
                    break
                task = asyncio.ensure_future(
                    self._handle(msg, writer, wlock))
                tasks.add(task)
                task.add_done_callback(tasks.discard)
        finally:
            for task in tasks:
                task.cancel()
            writer.close()
            try:
                await writer.wait_closed()
            except (ConnectionError, OSError):
                pass

    async def _respond(self, writer, wlock, msg: dict) -> None:
        async with wlock:
            write_frame(writer, msg)
            try:
                await writer.drain()
            except (ConnectionError, OSError):
                pass                     # client hung up mid-answer

    async def _handle(self, msg: dict, writer, wlock) -> None:
        rid = msg.get("id")
        op = msg.get("op")
        t_in = time.perf_counter()
        self.requests += 1
        try:
            # the chaos harness's replica trigger: a kill/hang/error
            # rule here IS "the replica died/hung mid-traffic".
            # Exception (not just OSError): an exc=runtime rule must
            # still produce a typed answer, never an unanswered id
            # the client only resolves by burning its whole timeout
            faults.fire("rpc.request")
        except Exception as e:
            await self._respond(writer, wlock,
                                {"id": rid, "ok": False,
                                 "error": "ServerError",
                                 "message": f"injected: {e}"})
            return
        if op == "ping":
            health = None
            h = getattr(self.backend, "health", None)
            if callable(h):
                try:
                    health = h().get("score")
                except Exception:
                    health = None
            await self._respond(writer, wlock,
                                {"id": rid, "ok": True, "pong": True,
                                 "health": health})
            return
        if op != "lookup" or "node" in msg and not isinstance(
                msg.get("node"), int):
            await self._respond(writer, wlock,
                                {"id": rid, "ok": False,
                                 "error": "ServerError",
                                 "message": f"bad request op={op!r}"})
            return
        budget_ms = msg.get("budget_ms")
        deadline = None
        if budget_ms is not None:
            deadline = t_in + float(budget_ms) / 1e3
            if float(budget_ms) <= 0.0:
                # spent before arrival: shed NOW, before the request
                # costs a batch slot (the deadline's whole point)
                self.shed_deadline += 1
                await self._respond(writer, wlock,
                                    {"id": rid, "ok": False,
                                     "error": "DeadlineExceeded",
                                     "message": "budget spent before "
                                                "arrival"})
                return
        try:
            kw = {"context": msg.get("ctx")}
            if self._takes_deadline:
                kw["deadline"] = deadline
            if self._takes_tenant and msg.get("tenant") is not None:
                # tenant rides the wire as plain request metadata; a
                # backend without a registry (no `tenant` parameter)
                # simply never sees it
                kw["tenant"] = str(msg["tenant"])
            fut = self.backend.submit(int(msg["node"]), **kw)
        except BaseException as e:
            name, text = _wire_error_of(e)
            await self._respond(writer, wlock,
                                {"id": rid, "ok": False, "error": name,
                                 "message": text})
            return
        try:
            timeout = (None if deadline is None
                       else max(deadline - time.perf_counter(), 0.0))
            row = await asyncio.wait_for(asyncio.wrap_future(fut),
                                         timeout=timeout)
        except asyncio.TimeoutError:
            self.shed_deadline += 1
            fut.cancel()
            await self._respond(writer, wlock,
                                {"id": rid, "ok": False,
                                 "error": "DeadlineExceeded",
                                 "message": "deadline passed while "
                                            "queued/dispatched"})
            return
        except asyncio.CancelledError:
            fut.cancel()
            raise
        except BaseException as e:
            name, text = _wire_error_of(e)
            await self._respond(writer, wlock,
                                {"id": rid, "ok": False, "error": name,
                                 "message": text})
            return
        await self._respond(writer, wlock,
                            {"id": rid, "ok": True,
                             "row": np.asarray(row, np.float32)
                             .ravel().tolist()})


# -- the client ---------------------------------------------------------------


class _Conn:
    """One multiplexed connection to one replica (client side, lives on
    the client's loop): pending requests correlated by id; a transport
    failure fails EVERY pending request with ReplicaUnavailable."""

    def __init__(self, name: str):
        self.name = name
        self.reader: Optional[asyncio.StreamReader] = None
        self.writer: Optional[asyncio.StreamWriter] = None
        self.pending: Dict[int, asyncio.Future] = {}
        self.wlock = asyncio.Lock()
        self._reader_task: Optional[asyncio.Task] = None

    async def open(self, host: str, port: int, timeout: float) -> None:
        self.reader, self.writer = await asyncio.wait_for(
            asyncio.open_connection(host, port), timeout=timeout)
        self._reader_task = asyncio.ensure_future(self._read_loop())

    async def _read_loop(self) -> None:
        err: BaseException = ReplicaUnavailable(
            f"{self.name}: connection closed")
        try:
            while True:
                msg = await read_frame(self.reader)
                if msg is None:
                    break
                fut = self.pending.pop(msg.get("id"), None)
                if fut is not None and not fut.done():
                    fut.set_result(msg)
        except (ConnectionError, OSError) as e:
            err = ReplicaUnavailable(f"{self.name}: {e}")
        finally:
            for fut in self.pending.values():
                if not fut.done():
                    fut.set_exception(err)
            self.pending.clear()

    @property
    def alive(self) -> bool:
        t = self._reader_task
        return t is not None and not t.done()

    async def call(self, msg: dict, timeout: Optional[float]) -> dict:
        fut: asyncio.Future = asyncio.get_running_loop().create_future()
        self.pending[msg["id"]] = fut
        try:
            async with self.wlock:
                write_frame(self.writer, msg)
                await self.writer.drain()
            return await asyncio.wait_for(fut, timeout=timeout)
        except (ConnectionError, OSError) as e:
            raise ReplicaUnavailable(f"{self.name}: {e}") from None
        finally:
            self.pending.pop(msg["id"], None)

    async def close(self) -> None:
        if self._reader_task is not None:
            self._reader_task.cancel()
        if self.writer is not None:
            self.writer.close()
            try:
                await self.writer.wait_closed()
            except (ConnectionError, OSError):
                pass


class RpcClient:
    """Deadline/retry/hedge client over N replicas (see module doc).

    ``replicas`` is ``{name: (host, port)}`` (or a list — names default
    ``r0..``). ``router`` (a ``fleet.HealthRouter``) ranks replicas by
    health for routing and hedging; without one a seeded rotation
    spreads load. The client owns one daemon loop thread; ``lookup``
    blocks, ``lookup_future`` returns a ``concurrent.futures.Future``.

    Policy knobs: ``timeout_ms`` per attempt (clamped to the remaining
    deadline budget), ``retries`` additional attempts after the first
    (each on the next-healthiest replica, after capped-exponential
    full-jitter backoff), ``hedge=True`` arms hedged requests (the
    hedge fires after the observed p95 of recent request latencies,
    clamped to ``[hedge_floor_ms, timeout_ms/2]``; a fixed
    ``hedge_delay_ms`` overrides). ``stats()`` reports attempts,
    retries, hedges, hedge wins, and typed-error counts."""

    def __init__(self, replicas, router=None, timeout_ms: float = 1000.0,
                 retries: int = 3, backoff_ms: float = 25.0,
                 backoff_cap_ms: float = 1000.0, hedge: bool = True,
                 hedge_delay_ms: Optional[float] = None,
                 hedge_floor_ms: float = 5.0,
                 connect_timeout_ms: float = 2000.0, seed: int = 0):
        if isinstance(replicas, dict):
            items = list(replicas.items())
        else:
            items = [(f"r{i}", a) for i, a in enumerate(replicas)]
        if not items:
            raise ValueError("need at least one replica address")
        self.addrs: Dict[str, Tuple[str, int]] = {
            n: (str(h), int(p)) for n, (h, p) in items}
        self.router = router
        self.timeout_ms = float(timeout_ms)
        self.retries = int(retries)
        self.backoff_ms = float(backoff_ms)
        self.backoff_cap_ms = float(backoff_cap_ms)
        self.hedge = bool(hedge)
        self.hedge_delay_ms = hedge_delay_ms
        self.hedge_floor_ms = float(hedge_floor_ms)
        self.connect_timeout_ms = float(connect_timeout_ms)
        self._rng = random.Random(seed)
        self._rotation = 0
        self._ids = iter(range(1, 1 << 62))
        self._conns: Dict[str, _Conn] = {}
        # per-replica open serialization (loop-thread only): two
        # concurrent lookups racing a reconnect must share ONE
        # connection, not leak the loser's socket + reader task
        self._open_locks: Dict[str, asyncio.Lock] = {}
        self._lat_ms: collections.deque = collections.deque(maxlen=256)
        self._lock = threading.Lock()
        self._stats = {"requests": 0, "attempts": 0, "retries": 0,
                       "hedges": 0, "hedge_wins": 0, "deadline_shed": 0}
        self._errors: collections.Counter = collections.Counter()
        self._closed = False
        self._loop = asyncio.new_event_loop()
        self._thread = threading.Thread(target=self._run_loop,
                                        name="qt-rpc-client",
                                        daemon=True)
        self._thread.start()

    def _run_loop(self) -> None:
        asyncio.set_event_loop(self._loop)
        self._loop.run_forever()
        self._loop.close()

    # -- routing -------------------------------------------------------------
    def _ranked(self, exclude: Sequence[str],
                seed=None) -> List[str]:
        """Replicas to try for one attempt. With a router: the PRIMARY
        is a health-WEIGHTED pick (load spreads away from pressed
        replicas), the rest follow healthiest-first (what the hedge
        and any fallback walk). Without one, a deterministic rotation
        spreads load. ``seed`` (the request's node id) is forwarded to
        locality-aware routers so partition ownership biases the draw;
        routers without the kwarg keep working (pure health)."""
        names = [n for n in self.addrs if n not in exclude]
        if not names:
            names = list(self.addrs)     # all excluded: try anyway
        if self.router is not None:
            try:
                ranked = [n for n in self.router.ranked(
                              exclude=exclude, seed=seed)
                          if n in self.addrs]
            except TypeError:            # router without seed kwarg
                ranked = [n for n in self.router.ranked(exclude=exclude)
                          if n in self.addrs]
            try:
                try:
                    primary = self.router.pick(exclude=exclude,
                                               seed=seed)
                except TypeError:        # router without seed kwarg
                    primary = self.router.pick(exclude=exclude)
            except ValueError:
                primary = None
            if primary in self.addrs:
                ranked = [primary] + [n for n in ranked
                                      if n != primary]
            if ranked:
                return ranked + [n for n in names if n not in ranked]
        with self._lock:
            k = self._rotation
            self._rotation += 1
        return names[k % len(names):] + names[:k % len(names)]

    def _hedge_delay_s(self) -> float:
        if self.hedge_delay_ms is not None:
            return self.hedge_delay_ms / 1e3
        with self._lock:
            lats = sorted(self._lat_ms)
        if len(lats) >= 8:
            p95 = lats[min(int(0.95 * len(lats)), len(lats) - 1)]
        else:
            p95 = self.timeout_ms / 4.0
        return min(max(p95, self.hedge_floor_ms),
                   self.timeout_ms / 2.0) / 1e3

    # -- the call path (coroutines, client loop) ------------------------------
    async def _conn_of(self, name: str) -> _Conn:
        conn = self._conns.get(name)
        if conn is not None and conn.alive:
            return conn
        lock = self._open_locks.setdefault(name, asyncio.Lock())
        async with lock:
            conn = self._conns.get(name)     # the race winner's conn
            if conn is not None and conn.alive:
                return conn
            if conn is not None:
                await conn.close()
            conn = _Conn(name)
            host, port = self.addrs[name]
            try:
                await conn.open(host, port,
                                self.connect_timeout_ms / 1e3)
            except (ConnectionError, OSError,
                    asyncio.TimeoutError) as e:
                raise ReplicaUnavailable(
                    f"{name}: connect failed: {e}") from None
            self._conns[name] = conn
            return conn

    async def _call_replica(self, name: str, node: int,
                            budget_ms: Optional[float],
                            ctx: Optional[dict],
                            timeout_s: float,
                            tid: Optional[int] = None,
                            hedge: bool = False,
                            tenant: Optional[str] = None) -> np.ndarray:
        # with tracing on, each dispatch leaves an `rpc.attempt` (or
        # `rpc.hedge`) span under the request's trace_id — retries and
        # hedge races are visible per replica in the assembled trace
        if tid is None:
            return await self._call_replica_raw(name, node, budget_ms,
                                                ctx, timeout_s, tenant)
        t0 = time.perf_counter()
        span = "rpc.hedge" if hedge else "rpc.attempt"
        try:
            row = await self._call_replica_raw(name, node, budget_ms,
                                               ctx, timeout_s, tenant)
        except asyncio.CancelledError:
            # a cancelled hedge loser is NOT an outcome — the winner's
            # span tells the request's story; recording
            # error=CancelledError here would make the tail sampler's
            # `error` policy keep every hedge-raced SUCCESS
            raise
        except BaseException as e:
            tracing.record(span, t0, time.perf_counter() - t0, tid,
                           {"replica": name,
                            "error": type(e).__name__})
            raise
        tracing.record(span, t0, time.perf_counter() - t0, tid,
                       {"replica": name})
        return row

    async def _call_replica_raw(self, name: str, node: int,
                                budget_ms: Optional[float],
                                ctx: Optional[dict],
                                timeout_s: float,
                                tenant: Optional[str] = None
                                ) -> np.ndarray:
        conn = await self._conn_of(name)
        msg = {"op": "lookup", "id": next(self._ids), "node": int(node)}
        if budget_ms is not None:
            msg["budget_ms"] = round(float(budget_ms), 3)
        if ctx:
            msg["ctx"] = ctx
        if tenant is not None:
            msg["tenant"] = str(tenant)
        try:
            resp = await conn.call(msg, timeout_s)
        except asyncio.TimeoutError:
            raise AttemptTimeout(
                f"{name}: no answer within {timeout_s * 1e3:.0f} ms") \
                from None
        if resp.get("ok"):
            return np.asarray(resp["row"], np.float32)
        err = _WIRE_ERRORS.get(resp.get("error"), RpcError)
        raise err(f"{name}: {resp.get('message', resp.get('error'))}")

    async def _attempt(self, names: List[str], node: int,
                       remaining_ms: Optional[float],
                       ctx: Optional[dict],
                       causes: List[BaseException],
                       dispatched: List[str],
                       tid: Optional[int] = None,
                       tenant: Optional[str] = None) -> np.ndarray:
        """One attempt = a primary call plus (optionally) one hedge to
        the next-ranked replica once the hedge delay passes unanswered.
        First answer wins; the loser is cancelled (idempotent serve
        lookups make the duplicate safe). Every replica actually
        dispatched to lands in ``dispatched`` — the retry loop
        excludes them all, so the next attempt spends its budget on an
        UNTOUCHED replica, not the hedge target that just failed."""
        timeout_s = self.timeout_ms / 1e3
        if remaining_ms is not None:
            timeout_s = min(timeout_s, max(remaining_ms, 1.0) / 1e3)
        primary = asyncio.ensure_future(self._call_replica(
            names[0], node, remaining_ms, ctx, timeout_s, tid,
            tenant=tenant))
        dispatched.append(names[0])
        tasks = {primary: names[0]}
        if self.hedge and len(names) > 1:
            delay = self._hedge_delay_s()
            done, _ = await asyncio.wait({primary}, timeout=delay)
            if not done:
                with self._lock:
                    self._stats["hedges"] += 1
                left_ms = (None if remaining_ms is None
                           else max(remaining_ms - delay * 1e3, 1.0))
                hedge = asyncio.ensure_future(self._call_replica(
                    names[1], node, left_ms, ctx,
                    max(timeout_s - delay, 1e-3), tid, hedge=True,
                    tenant=tenant))
                dispatched.append(names[1])
                tasks[hedge] = names[1]
        pending = set(tasks)
        result = None
        got = False
        while pending and not got:
            done, pending = await asyncio.wait(
                pending, return_when=asyncio.FIRST_COMPLETED)
            for task in done:
                if task.exception() is None and not got:
                    got = True
                    result = task.result()
                    if task is not primary:
                        with self._lock:
                            self._stats["hedge_wins"] += 1
                elif task.exception() is not None:
                    causes.append(task.exception())
        for task in pending:
            task.cancel()                # first answer won: cancel dup
        if got:
            return result
        raise causes[-1]

    async def _lookup(self, node: int, budget_ms: Optional[float],
                      ctx: Optional[dict],
                      tenant: Optional[str] = None) -> np.ndarray:
        if not tracing.enabled():
            return await self._lookup_inner(node, budget_ms, ctx, None,
                                            tenant)
        # the client's ROOT span (`rpc.lookup`) closes the trace on
        # this side of the wire — the tail sampler's completion
        # signal; a failed lookup closes it error-stamped, so the
        # client keeps exactly the traces its user saw fail
        c = tracing.extract(ctx)
        tid = c.trace_id if c is not None else tracing.new_global_trace_id()
        t0 = time.perf_counter()
        try:
            row = await self._lookup_inner(node, budget_ms, ctx, tid,
                                           tenant)
        except asyncio.CancelledError:
            # a cancelled lookup (caller cancelled the future, client
            # shutting down) is NOT a failed request — no root span,
            # or the `error` policy would keep every such trace
            raise
        except BaseException as e:
            tracing.record("rpc.lookup", t0, time.perf_counter() - t0,
                           tid, {"node": int(node),
                                 "error": type(e).__name__})
            raise
        tracing.record("rpc.lookup", t0, time.perf_counter() - t0, tid,
                       {"node": int(node)})
        return row

    async def _lookup_inner(self, node: int, budget_ms: Optional[float],
                            ctx: Optional[dict],
                            tid: Optional[int],
                            tenant: Optional[str] = None) -> np.ndarray:
        t0 = time.perf_counter()
        deadline = (None if budget_ms is None
                    else t0 + float(budget_ms) / 1e3)
        causes: List[BaseException] = []
        tried: List[str] = []
        for attempt in range(self.retries + 1):
            remaining_ms = None
            if deadline is not None:
                remaining_ms = (deadline - time.perf_counter()) * 1e3
                if remaining_ms <= 0:
                    with self._lock:
                        self._stats["deadline_shed"] += 1
                        self._errors["DeadlineExceeded"] += 1
                    raise DeadlineExceeded(
                        f"budget spent after {attempt} attempts "
                        f"({[type(c).__name__ for c in causes]})")
            names = self._ranked(exclude=tried, seed=node)
            with self._lock:
                self._stats["attempts"] += 1
                if attempt:
                    self._stats["retries"] += 1
            dispatched: List[str] = []
            try:
                row = await self._attempt(names, node, remaining_ms,
                                          ctx, causes, dispatched, tid,
                                          tenant)
                with self._lock:
                    self._lat_ms.append(
                        (time.perf_counter() - t0) * 1e3)
                return row
            except RpcError as e:
                if e.error not in _RETRIABLE:
                    with self._lock:
                        self._errors[e.error] += 1
                    raise
            tried.extend(n for n in dispatched if n not in tried)
            if attempt < self.retries:
                # capped exponential backoff, FULL jitter: the whole
                # delay is uniform in [0, cap] — the discipline that
                # de-synchronizes a thundering herd of retriers
                cap_ms = min(self.backoff_cap_ms,
                             self.backoff_ms * (2 ** attempt))
                delay_ms = self._rng.uniform(0.0, cap_ms)
                if deadline is not None:
                    delay_ms = min(
                        delay_ms,
                        max((deadline - time.perf_counter()) * 1e3
                            - 1.0, 0.0))
                if delay_ms > 0:
                    t_back = time.perf_counter()
                    await asyncio.sleep(delay_ms / 1e3)
                    if tid is not None:
                        tracing.record("rpc.backoff", t_back,
                                       time.perf_counter() - t_back,
                                       tid, {"attempt": attempt})
        with self._lock:
            self._errors["AllAttemptsFailed"] += 1
        raise AllAttemptsFailed(
            f"{self.retries + 1} attempts failed for node {node}: "
            f"{[f'{type(c).__name__}: {c}' for c in causes[-4:]]}",
            causes)

    # -- the sync facade ------------------------------------------------------
    def lookup_future(self, node: int, budget_ms: Optional[float] = None,
                      context: Optional[dict] = None,
                      tenant: Optional[str] = None):
        """Submit one lookup; returns a ``concurrent.futures.Future``
        resolving to the float32 logits row or raising a typed
        :class:`RpcError`. ``tenant`` (a tenant-class name) rides the
        wire as request metadata — replicas with a tenant registry
        apply their per-tenant SLO accounting + shed-order policy;
        replicas without one ignore it."""
        if self._closed:
            raise ServerClosed("rpc client is closed")
        if tracing.enabled():
            # mint + inject a global trace context so the replica's
            # serve spans and this client's rpc spans share one
            # trace_id — the fleet assembler's stitch key. Caller
            # metadata without a context gets stamped into a COPY
            # (the caller's dict is not ours to mutate); a context
            # the caller already injected passes through untouched.
            if context is None:
                context = tracing.inject({})
            elif tracing.extract(context) is None:
                context = tracing.inject(dict(context))
        with self._lock:
            self._stats["requests"] += 1
        return asyncio.run_coroutine_threadsafe(
            self._lookup(int(node), budget_ms, context, tenant),
            self._loop)

    def lookup(self, node: int, budget_ms: Optional[float] = None,
               context: Optional[dict] = None,
               tenant: Optional[str] = None) -> np.ndarray:
        """Blocking :meth:`lookup_future`."""
        timeout = None
        if budget_ms is not None:
            # generous host-side guard: the coroutine enforces the real
            # deadline; this only stops a wedged loop from hanging the
            # caller forever
            timeout = budget_ms / 1e3 + 30.0
        return self.lookup_future(node, budget_ms, context,
                                  tenant).result(timeout=timeout)

    def ping(self, name: str, timeout_ms: float = 1000.0) -> dict:
        """One ``ping`` to a named replica (health probe)."""
        async def _ping():
            conn = await self._conn_of(name)
            return await conn.call({"op": "ping", "id": next(self._ids)},
                                   timeout_ms / 1e3)
        return asyncio.run_coroutine_threadsafe(
            _ping(), self._loop).result(timeout=timeout_ms / 1e3 + 10.0)

    def stats(self) -> dict:
        """Requests/attempts/retries/hedges + typed-error counts +
        the observed latency p50/p95 the hedge delay derives from."""
        with self._lock:
            s = dict(self._stats)
            s["errors"] = dict(self._errors)
            lats = sorted(self._lat_ms)
        if lats:
            s["lat_p50_ms"] = round(lats[len(lats) // 2], 3)
            s["lat_p95_ms"] = round(
                lats[min(int(0.95 * len(lats)), len(lats) - 1)], 3)
        s["hedge_delay_ms"] = round(self._hedge_delay_s() * 1e3, 3)
        return s

    # -- life cycle -----------------------------------------------------------
    def close(self) -> None:
        """Close every connection, stop the loop thread. Idempotent."""
        if self._closed:
            return
        self._closed = True

        async def _shutdown():
            for conn in list(self._conns.values()):
                await conn.close()
            self._conns.clear()
            asyncio.get_running_loop().stop()

        try:
            asyncio.run_coroutine_threadsafe(_shutdown(), self._loop)
        except RuntimeError:
            pass
        if self._thread is not threading.current_thread():
            self._thread.join(timeout=10.0)

    @property
    def closed(self) -> bool:
        return self._closed

    def __enter__(self) -> "RpcClient":
        return self

    def __exit__(self, *exc) -> None:
        self.close()
