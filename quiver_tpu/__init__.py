"""quiver_tpu — a TPU-native graph-learning data framework.

Re-implements the *capabilities* of torch-quiver (GPU-accelerated GNN
sampling + tiered feature collection; reference public API at
srcs/python/quiver/__init__.py:1-17) with a JAX/XLA/Pallas-first design:

- graph sampling   -> static-shape, key-threaded samplers (Pallas reservoir
                      kernel on TPU; jnp reference implementation as oracle)
- feature storage  -> HBM cache + host tier, replicated or GSPMD-sharded
                      over a `jax.sharding.Mesh` (the ICI generalization of
                      the reference's NVLink "p2p clique")
- multi-host comm  -> XLA collectives (`all_to_all`/`psum`) over ICI/DCN
                      instead of a hand-rolled NCCL wrapper
"""

__version__ = "0.1.0"

from .utils import (
    CSRTopo,
    parse_size,
    reindex_by_config,
    reindex_feature,
    Topo,
    init_p2p,
)
from .feature import (Feature, DeviceConfig, DistFeature,
                      ExchangeCapPlan, PartitionInfo)
from .shard_tensor import ShardTensor, ShardTensorConfig
from .pyg import GraphSageSampler, MixedGraphSageSampler, SampleJob
from .comm import TpuComm, HostRankTable, get_comm_id
from .partition import (
    quiver_partition_feature,
    load_quiver_feature_partition,
    partition_feature_without_replication,
    save_quantized_feature_partition,
    load_quantized_feature_partition,
    save_disk_tier,
    load_disk_tier,
    load_disk_tier_store,
)
from .ops.quant import QuantizedTensor, plan_hot_capacity
from .hetero import HeteroCSRTopo, HeteroGraphSageSampler
from .hetero_feature import HeteroFeature
from .async_sampler import (AsyncNeighborSampler, AsyncCudaNeighborSampler,
                            sample_ahead)
from .prefetch import ColdPrefetcher, StagingRing
from .io import ExtentReader, StorageModel, plan_extents
from .debug import show_tensor_info
from .inference import layerwise_inference
from .datasets import (GraphDataset, from_numpy_dir,
                       generate_drifting_trace,
                       generate_synthetic_cold_dataset,
                       load_synthetic_cold_dataset)
from .pipeline import Pipeline, pipelined
from .metrics import Collector, MetricsSink, SloBudget, StepStats
from .serving import (MicroBatchServer, OverloadError, ServeConfig,
                      ServeEngine, ShardedServeEngine, TenantClass,
                      build_serve_step, build_sharded_serve_step,
                      default_tenant_classes)
from .traffic import generate_scenario, replay
from .tailsampling import TailSampler, TraceStore
from .telemetry import FlightRecorder, PlanContext, TelemetryHub
from .profile import StageProfiler, machine_probe
from .fleet import (FleetAggregator, FleetExporter, HealthRouter,
                    ReplicaSupervisor, health_score)
from .actuator import Actuator, FleetAutoscaler, Knob
from .faults import FaultPlan, FaultRule
from .rpc import (RpcClient, RpcError, RpcServer, DeadlineExceeded,
                  ServerClosed)
from . import (actuator, analysis, capacity, comm, profiling,
               checkpoint, datasets, debug, faults, fleet, metrics,
               profile, rpc, serving, tailsampling, telemetry, tracing,
               traffic)

# torch-quiver compatible aliases (reference __init__.py exports these names)
p2pCliqueTopo = Topo
NcclComm = TpuComm
getNcclId = get_comm_id

__all__ = [
    "GraphDataset",
    "from_numpy_dir",
    "generate_drifting_trace",
    "generate_synthetic_cold_dataset",
    "load_synthetic_cold_dataset",
    "CSRTopo",
    "parse_size",
    "reindex_by_config",
    "reindex_feature",
    "Topo",
    "p2pCliqueTopo",
    "init_p2p",
    "Feature",
    "DeviceConfig",
    "DistFeature",
    "ExchangeCapPlan",
    "PartitionInfo",
    "ShardTensor",
    "ShardTensorConfig",
    "GraphSageSampler",
    "MixedGraphSageSampler",
    "SampleJob",
    "TpuComm",
    "NcclComm",
    "HostRankTable",
    "get_comm_id",
    "getNcclId",
    "quiver_partition_feature",
    "load_quiver_feature_partition",
    "partition_feature_without_replication",
    "save_quantized_feature_partition",
    "load_quantized_feature_partition",
    "QuantizedTensor",
    "plan_hot_capacity",
    "HeteroCSRTopo",
    "HeteroFeature",
    "HeteroGraphSageSampler",
    "AsyncNeighborSampler",
    "AsyncCudaNeighborSampler",
    "sample_ahead",
    "ColdPrefetcher",
    "StagingRing",
    "ExtentReader",
    "StorageModel",
    "plan_extents",
    "save_disk_tier",
    "load_disk_tier",
    "load_disk_tier_store",
    "show_tensor_info",
    "layerwise_inference",
    "Pipeline",
    "pipelined",
    "Collector",
    "MetricsSink",
    "SloBudget",
    "StepStats",
    "MicroBatchServer",
    "OverloadError",
    "ServeConfig",
    "ServeEngine",
    "ShardedServeEngine",
    "TenantClass",
    "default_tenant_classes",
    "build_serve_step",
    "build_sharded_serve_step",
    "generate_scenario",
    "replay",
    "TailSampler",
    "TraceStore",
    "TelemetryHub",
    "PlanContext",
    "FlightRecorder",
    "StageProfiler",
    "machine_probe",
    "FleetAggregator",
    "FleetExporter",
    "HealthRouter",
    "ReplicaSupervisor",
    "health_score",
    "Actuator",
    "FleetAutoscaler",
    "Knob",
    "FaultPlan",
    "FaultRule",
    "RpcClient",
    "RpcError",
    "RpcServer",
    "DeadlineExceeded",
    "ServerClosed",
]
