"""Layer-wise full-graph inference.

The evaluation-side counterpart of sampled training (the reference
examples run PyG's ``subgraph_loader`` inference, e.g.
train_quiver_multi_node.py:379): compute exact (non-sampled) embeddings
layer by layer over all nodes, batching nodes per step so the full graph
never needs to fit activation memory.

TPU design: per layer, nodes are processed in fixed-size batches. Each
batch's in-neighborhood is reduced over ``ceil(max_deg_in_batch /
max_degree)`` fixed-shape windows of ``max_degree`` neighbors, so the
aggregation is EXACT for arbitrary degree (ogbn-products hub nodes reach
tens of thousands of neighbors) while every dispatch keeps a static
[batch, max_degree] shape. Non-hub batches take exactly one window, so
the common case costs the same as a fixed-cap gather.
"""

from __future__ import annotations

import functools
from typing import Callable

import jax
import jax.numpy as jnp
import numpy as np


def neighborhood_block(indptr, indices, nodes, max_degree, window=0):
    """For each node: in-neighbors at row positions
    [window*max_degree, (window+1)*max_degree), padded/masked to
    [bs, max_degree]. ``window`` may be a traced scalar."""
    n = indptr.shape[0] - 1
    e = indices.shape[0]
    safe = jnp.clip(nodes, 0, n - 1).astype(indptr.dtype)
    base = jnp.asarray(window, indptr.dtype) * max_degree
    start = indptr[safe] + base
    deg = (indptr[safe + 1] - indptr[safe]).astype(jnp.int32)
    rel = deg - base.astype(jnp.int32)
    offs = jnp.arange(max_degree, dtype=jnp.int32)[None, :]
    gather = jnp.clip(start[:, None] + offs, 0, e - 1)
    nbrs = indices[gather].astype(jnp.int32)
    mask = (offs < rel[:, None]) & (nodes >= 0)[:, None]
    return jnp.where(mask, nbrs, -1), deg


def layerwise_inference(apply_layer: Callable, indptr, indices,
                        x: jax.Array, num_layers: int,
                        batch_size: int = 4096,
                        max_degree: int = 256) -> jax.Array:
    """Run ``num_layers`` rounds of exact message passing.

    ``apply_layer(layer_idx, x_self, mean_agg) -> new_x`` computes one
    layer for a node batch given its [bs, F] self features and the
    [bs, F] EXACT mean of all neighbor features (zeros for isolated
    nodes). The mean is accumulated here over degree windows, so no
    degree cap applies — ``max_degree`` only sets the window width
    (dispatch granularity), not a truncation.
    """
    n = indptr.shape[0] - 1
    host_indptr = np.asarray(indptr)
    if (host_indptr.dtype == np.int64
            and host_indptr[-1] > np.iinfo(np.int32).max
            and not jax.config.jax_enable_x64):
        raise ValueError(
            "layerwise_inference: edge offsets exceed int32 in 32-bit jax "
            "mode; jnp.asarray would silently wrap them — enable "
            "jax_enable_x64 or run inference shard-wise (each shard's "
            "local edge count < 2^31)")
    host_deg = host_indptr[1:] - host_indptr[:-1]
    indptr = jnp.asarray(indptr)
    indices = jnp.asarray(indices)

    # acc is donated: the window loop re-feeds it every iteration, so
    # XLA accumulates in place instead of allocating a fresh
    # [batch, dim] buffer per window (hub batches run many windows)
    @functools.partial(jax.jit, donate_argnums=(3,))
    def window_sum(x_all, nodes, w, acc):
        nbrs, _ = neighborhood_block(indptr, indices, nodes, max_degree, w)
        xn = x_all[jnp.clip(nbrs, 0, n - 1)]
        m = (nbrs >= 0).astype(x_all.dtype)
        return acc + (xn * m[:, :, None]).sum(axis=1)

    @functools.partial(jax.jit, static_argnums=0)
    def finalize(layer_idx, x_all, nodes, acc):
        safe = jnp.clip(nodes, 0, n - 1)
        deg = (indptr[safe + 1] - indptr[safe]).astype(x_all.dtype)
        mean = acc / jnp.maximum(deg, 1.0)[:, None]
        return apply_layer(layer_idx, x_all[safe], mean)

    for layer in range(num_layers):
        outs = []
        for lo in range(0, n, batch_size):
            hi = min(lo + batch_size, n)
            nodes = jnp.arange(lo, hi, dtype=jnp.int32)
            if nodes.shape[0] < batch_size:
                nodes = jnp.concatenate([
                    nodes, jnp.full((batch_size - nodes.shape[0],), -1,
                                    jnp.int32)])
            windows = max(1, -(-int(host_deg[lo:hi].max(initial=0))
                               // max_degree))
            acc = jnp.zeros((batch_size, x.shape[1]), x.dtype)
            for w in range(windows):
                acc = window_sum(x, nodes, jnp.int32(w), acc)
            outs.append(finalize(layer, x, nodes, acc))
        x = jnp.concatenate(outs)[:n]
    return x


def sage_apply_layer(params_list, activation=jax.nn.relu):
    """apply_layer for a stack of SAGEConv params
    ({'lin_root': {kernel, bias}, 'lin_nbr': {kernel}})."""
    def apply(layer_idx, x_self, mean_nbr):
        p = params_list[layer_idx]
        h = x_self @ p["lin_root"]["kernel"] + p["lin_root"]["bias"]
        h = h + mean_nbr @ p["lin_nbr"]["kernel"]
        if layer_idx < len(params_list) - 1:
            h = activation(h)
        return h
    return apply
