"""Layer-wise full-graph inference.

The evaluation-side counterpart of sampled training (the reference
examples run PyG's ``subgraph_loader`` inference, e.g.
train_quiver_multi_node.py:379): compute exact (non-sampled) embeddings
layer by layer over all nodes, batching nodes per step so the full graph
never needs to fit activation memory.

TPU design: per layer, nodes are processed in fixed-size batches; each
batch gathers its FULL in-neighborhood rows (capped at ``max_degree``
with masking — exact for graphs whose max in-degree fits, top-``max_
degree`` truncation otherwise), so each layer is one jitted program run
repeatedly.
"""

from __future__ import annotations

import functools
from typing import Callable, Sequence

import jax
import jax.numpy as jnp
import numpy as np


def neighborhood_block(indptr, indices, nodes, max_degree):
    """For each node: its in-neighbors padded to [bs, max_degree]."""
    n = indptr.shape[0] - 1
    e = indices.shape[0]
    safe = jnp.clip(nodes, 0, n - 1).astype(indptr.dtype)
    start = indptr[safe]
    deg = (indptr[safe + 1] - start).astype(jnp.int32)
    offs = jnp.arange(max_degree, dtype=jnp.int32)[None, :]
    gather = jnp.clip(start[:, None] + offs, 0, e - 1)
    nbrs = indices[gather].astype(jnp.int32)
    mask = (offs < deg[:, None]) & (nodes >= 0)[:, None]
    return jnp.where(mask, nbrs, -1), deg


def layerwise_inference(apply_layer: Callable, indptr, indices,
                        x: jax.Array, num_layers: int,
                        batch_size: int = 4096,
                        max_degree: int = 256) -> jax.Array:
    """Run ``num_layers`` rounds of exact message passing.

    ``apply_layer(layer_idx, x_self, x_nbrs, nbr_mask) -> new_x`` computes
    one layer for a node batch given [bs, F] self features and
    [bs, max_degree, F] neighbor features (masked).
    """
    n = indptr.shape[0] - 1
    indptr = jnp.asarray(indptr)
    indices = jnp.asarray(indices)

    @functools.partial(jax.jit, static_argnums=0)
    def run_batch(layer_idx, x_all, nodes):
        nbrs, _deg = neighborhood_block(indptr, indices, nodes, max_degree)
        x_self = x_all[jnp.clip(nodes, 0, n - 1)]
        x_nbrs = x_all[jnp.clip(nbrs, 0, n - 1)]
        mask = (nbrs >= 0).astype(x_all.dtype)
        return apply_layer(layer_idx, x_self, x_nbrs, mask)

    for layer in range(num_layers):
        outs = []
        for lo in range(0, n, batch_size):
            nodes = jnp.arange(lo, min(lo + batch_size, n), dtype=jnp.int32)
            if nodes.shape[0] < batch_size:
                nodes = jnp.concatenate([
                    nodes, jnp.full((batch_size - nodes.shape[0],), -1,
                                    jnp.int32)])
            outs.append(run_batch(layer, x, nodes))
        x = jnp.concatenate(outs)[:n]
    return x


def sage_apply_layer(params_list, activation=jax.nn.relu):
    """apply_layer for a stack of SAGEConv params
    ({'lin_root': {kernel, bias}, 'lin_nbr': {kernel}})."""
    def apply(layer_idx, x_self, x_nbrs, mask):
        p = params_list[layer_idx]
        cnt = jnp.maximum(mask.sum(axis=1, keepdims=True), 1.0)
        mean = (x_nbrs * mask[:, :, None]).sum(axis=1) / cnt
        h = x_self @ p["lin_root"]["kernel"] + p["lin_root"]["bias"]
        h = h + mean @ p["lin_nbr"]["kernel"]
        if layer_idx < len(params_list) - 1:
            h = activation(h)
        return h
    return apply
