"""Heterogeneous graph support: typed topology + relational k-hop sampler.

Covers the MAG240M-class workload (BASELINE configs[3]; reference
benchmarks/ogbn-mag240m). The reference trains on the homogeneous
paper-cites-paper projection (train_quiver_multi_node.py:90-93) — this
module supports that *and* true multi-relation sampling for R-GCN:

- ``HeteroCSRTopo``: one CSR per relation (src_type, rel, dst_type), each
  an ordinary ``CSRTopo`` over the dst-type id space with src-type ids as
  indices (CSR rows = dst nodes, matching the sampling direction:
  frontier nodes pull their in-neighbors).
- ``HeteroGraphSageSampler``: per hop, every relation samples ``k`` of
  the current dst-type frontier's neighbors; per node type, the frontier
  union is compacted with the same first-occurrence static-shape
  compaction as the homogeneous path.

All shapes static; same -1 masking contract as the homogeneous sampler.
"""

from __future__ import annotations

from typing import Dict, List, NamedTuple, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from .ops.sample import (as_index_rows, as_index_rows_overlapping,
                         compact_union, compose_slot_map, edge_row_ids,
                         reshuffle_csr, sample_layer,
                         sample_layer_exact_wide, sample_layer_rotation,
                         sample_layer_window, suggest_hub_cap)
from .ops.weighted import sample_layer_weighted
from .pyg.sage_sampler import Adj
from .utils import CSRTopo

EdgeType = Tuple[str, str, str]          # (src_type, relation, dst_type)


class HeteroCSRTopo:
    """Typed topology: ``rels[(src, rel, dst)] = CSRTopo`` whose row v
    (a dst-type node) lists its src-type in-neighbors."""

    def __init__(self, rels: Dict[EdgeType, CSRTopo],
                 node_counts: Dict[str, int]):
        self.rels = dict(rels)
        self.node_counts = dict(node_counts)
        for (src, rel, dst), topo in self.rels.items():
            if topo.node_count < self.node_counts.get(dst, 0):
                raise ValueError(
                    f"relation {(src, rel, dst)} CSR has {topo.node_count} "
                    f"rows < dst node_count {self.node_counts[dst]}")

    @property
    def edge_types(self) -> List[EdgeType]:
        return list(self.rels.keys())

    @property
    def node_types(self) -> List[str]:
        return list(self.node_counts.keys())


class HeteroLayer(NamedTuple):
    """One sampled hop of a hetero graph.

    adjs:     {edge_type: Adj} — local bipartite COO per relation; source
              local ids index the *next* frontier of the src type, target
              local ids index the current frontier of the dst type.
    frontier: {node_type: n_id array} AFTER this hop (input to next hop /
              feature gather), -1-filled static caps.
    counts:   {node_type: valid count in frontier}
    """

    adjs: Dict[EdgeType, Adj]
    frontier: Dict[str, jax.Array]
    counts: Dict[str, jax.Array]


class HeteroGraphSageSampler:
    """Relational neighbor sampler.

    ``sizes`` is a list of per-hop fanouts; each entry is either an int
    (same fanout for every relation) or a ``{edge_type: k}`` dict.
    ``sample(seeds)`` seeds are nodes of ``seed_type``.

    Performance modes (the same engine as the homogeneous sampler, per
    relation — the reference's MAG240M path only ever samples its
    homogeneous projection, train_quiver_multi_node.py:90-93, so each
    of these is beyond-parity):

    - ``sampling="exact"`` (default): i.i.d. Fisher-Yates draws through
      the wide-fetch path (``sample_layer_exact_wide``) — one/two row
      gathers per low-degree seed per relation, scattered loads only
      for hubs. No reshuffle needed.
    - ``sampling="rotation"`` / ``"window"``: the wide row-fetch draws
      over per-relation shuffled row views; call ``reshuffle()`` per
      epoch (automatic on first sample). ``shuffle="butterfly"`` is the
      ~40x cheaper composed epoch re-mix.
    - ``layout="overlap"``: one 256-wide gather per seed instead of two
      128-wide, at 2x index memory — per relation.

    ``frontier_cap`` bounds each node type's frontier capacity (an int,
    or ``{node_type: int}``): multi-relation expansion otherwise grows
    frontier caps multiplicatively per hop. Sampled edges whose source
    falls past the cap are masked (-1) — the same static-capacity
    truncation contract as every other capped shape here.

    ``edge_weight`` (``{edge_type: CSR-slot-aligned weights}``) switches
    the listed relations to weighted (attention) draws — with
    replacement, proportional to weight, the reference ``weight_sample``
    contract (cuda_random.cu.hpp:178-221); unlisted relations keep the
    uniform exact draw. ``with_eid=True`` stamps every sampled edge's
    ``Adj.e_id`` with its global edge id (the relation's
    ``CSRTopo.eid`` if set, else its CSR slot), -1 where masked —
    in every sampling mode (rotation/window compose per-relation
    permuted slot maps across ``reshuffle()``). ``edge_weight`` is
    exact-mode only (see the ctor guard).
    """

    def __init__(self, topo: HeteroCSRTopo, sizes: Sequence,
                 seed_type: str, seed: int = 0, sampling: str = "exact",
                 layout: str = "pair", shuffle: str = "sort",
                 frontier_cap=None, wide_exact: bool = True,
                 edge_weight: Dict[EdgeType, object] = None,
                 with_eid: bool = False):
        self.topo = topo
        self.seed_type = seed_type
        self.sizes = [s if isinstance(s, dict)
                      else {et: s for et in topo.edge_types}
                      for s in sizes]
        if sampling not in ("exact", "rotation", "window"):
            raise ValueError(f"unknown sampling method {sampling!r}")
        if layout not in ("pair", "overlap"):
            raise ValueError(f"unknown layout {layout!r}")
        if shuffle not in ("sort", "butterfly"):
            raise ValueError(f"unknown shuffle {shuffle!r}")
        max_k = max((k for hop in self.sizes for k in hop.values()),
                    default=0)
        if sampling in ("rotation", "window") and max_k > 128:
            raise ValueError(f"{sampling} sampling supports fanouts <= 128")
        self.sampling = sampling
        self.layout = layout
        self.shuffle = shuffle
        if frontier_cap is not None and not isinstance(frontier_cap, dict):
            frontier_cap = {t: int(frontier_cap) for t in topo.node_types}
        self.frontier_cap = frontier_cap
        # wide_exact=False: skip the per-relation layout views (+E/+2E
        # memory each) and keep the zero-extra-copy scattered exact draw
        self.wide_exact = wide_exact
        # per-relation CSR-slot-aligned weights => weighted (attention)
        # draws for those relations (with replacement, the reference
        # weight_sample contract — cuda_random.cu.hpp:178-221);
        # unlisted relations keep the uniform exact draw. Same coupled-
        # param strictness as the homogeneous ctor: the weighted
        # windowed draw's mandatory hub re-placement only exists on the
        # homogeneous rotation/window path, so WEIGHTED hetero sampling
        # is exact-mode only — an explicit error, not a silent
        # downgrade. (with_eid works in every mode; see below.)
        if edge_weight is not None:
            unknown = set(edge_weight) - set(topo.rels)
            if unknown:
                raise ValueError(
                    f"edge_weight for unknown relation(s) "
                    f"{sorted(unknown)}")
            if sampling != "exact":
                raise ValueError(
                    "per-relation weighted draws support "
                    "sampling='exact' only (rotation/window would need "
                    "the weighted windowed draw's co-permuted weight "
                    "rows — use the homogeneous GraphSageSampler for "
                    "that workload)")
            for et, w in edge_weight.items():
                e = int(topo.rels[et].indices.shape[0])
                # np.shape: no device transfer for the length check
                # (jnp.asarray would ship each E-sized array to HBM
                # just to read its shape)
                if int(np.shape(w)[0]) != e:
                    raise ValueError(
                        f"edge_weight[{et}] has {int(np.shape(w)[0])} "
                        f"entries, relation has {e} edges")
        self.edge_weight = edge_weight
        # with_eid works in every sampling mode: exact modes map raw
        # CSR slots through the relation's eid map; rotation/window
        # maintain per-relation CO-PERMUTED slot maps across reshuffles
        # (the homogeneous sampler's _rot_eid pattern, per relation).
        self.with_eid = with_eid
        self._weights_placed = None
        self._eids_placed = None
        self._rot_eids = {}      # {edge_type: permuted-slot -> edge id}
        self._key = jax.random.key(seed)
        self._fn_cache = {}
        self._hub_fracs = None   # {edge_type: static hub fraction}
        self._rows = None        # {edge_type: rows view}
        self._permuted = {}      # butterfly composition state
        self._row_ids = {}
        self._rels_placed = None  # {edge_type: (indptr, indices)}

    def next_key(self):
        self._key, sub = jax.random.split(self._key)
        return sub

    def _as_rows(self, flat):
        return (as_index_rows_overlapping(flat)
                if self.layout == "overlap" else as_index_rows(flat))

    @property
    def _stride(self):
        return 128 if self.layout == "overlap" else None

    def reshuffle(self, key=None):
        """Per-epoch refresh of every relation's shuffled row view
        (rotation/window freshness source; exact mode needs none)."""
        if self.sampling not in ("rotation", "window"):
            raise ValueError(
                "reshuffle only applies to rotation/window sampling")
        key = key if key is not None else self.next_key()
        bfly = self.shuffle == "butterfly"
        rows = {}
        for i, (et, t) in enumerate(sorted(self.topo.rels.items())):
            indices = jnp.asarray(t.indices)
            rid = self._row_ids.get(et)
            if rid is None:
                rid = jax.jit(edge_row_ids, static_argnums=1)(
                    jnp.asarray(t.indptr), int(indices.shape[0]))
                self._row_ids[et] = rid
            src = (self._permuted.get(et, indices) if bfly else indices)
            out = reshuffle_csr(src, rid, jax.random.fold_in(key, i),
                                method=self.shuffle,
                                with_slot_map=self.with_eid)
            if self.with_eid:
                permuted, smap = out
                # co-permuted edge-id map per relation (shared
                # composition semantics: ops.compose_slot_map). The
                # placed base eid is cached so sort mode doesn't
                # re-transfer E-sized maps every epoch.
                base = None
                if t.eid is not None:
                    if self._eids_placed is None:
                        self._eids_placed = {}
                    base = self._eids_placed.get(et)
                    if base is None:
                        base = jnp.asarray(t.eid)
                        self._eids_placed[et] = base
                self._rot_eids[et] = compose_slot_map(
                    self._rot_eids.get(et), smap, base, bfly)
            else:
                permuted = out
            if bfly:
                self._permuted[et] = permuted
            rows[et] = self._as_rows(permuted)
        self._rows = rows

    def _build(self, batch_size: int):
        sizes = self.sizes
        seed_type = self.seed_type
        node_types = self.topo.node_types
        method = self.sampling
        stride = self._stride
        caps = self.frontier_cap
        with_eid = self.with_eid
        hub_fracs = self._hub_fracs or {}

        # rels/rows enter as jit ARGUMENTS (pytrees), never closures: a
        # closed-over device array is embedded in the HLO as a literal
        # constant, and MAG240M-scale relations would overflow a remote
        # (tunnel) compile request — same hazard bench.py documents
        def run(seeds, key, rows, rels, weights, eids):
            frontier = {t: None for t in node_types}
            frontier[seed_type] = seeds.astype(jnp.int32)
            hops = []
            step = 0
            for hop, fanouts in enumerate(sizes):
                per_rel_samples: Dict[EdgeType, tuple] = {}
                # 1. sample every relation whose dst type has a frontier
                for et, k in fanouts.items():
                    src_t, _, dst_t = et
                    cur = frontier[dst_t]
                    if cur is None or k <= 0:
                        continue
                    sub = jax.random.fold_in(key, step)
                    step += 1
                    indptr, indices = rels[et]

                    def unpack(out):
                        # (nbrs, counts[, slots]) -> (nbrs, slots|None)
                        return (out[0], out[2] if with_eid else None)

                    w = weights.get(et)
                    if w is not None:
                        nbrs, slots = unpack(sample_layer_weighted(
                            indptr, indices, w, cur, k, sub,
                            with_slots=with_eid))
                    elif method == "rotation":
                        nbrs, slots = unpack(sample_layer_rotation(
                            indptr, rows[et], cur, k, sub, stride=stride,
                            with_slots=with_eid))
                    elif method == "window":
                        nbrs, slots = unpack(sample_layer_window(
                            indptr, rows[et], cur, k, sub, stride=stride,
                            with_slots=with_eid))
                    elif rows is not None:
                        # scattered-load budget from the relation's own
                        # cached degree-bucket split (CSRTopo metadata,
                        # shared across batch sizes and epochs); static
                        # because the frontier width is a compile-time
                        # shape
                        nbrs, slots = unpack(sample_layer_exact_wide(
                            indptr, indices, rows[et], cur, k, sub,
                            stride=stride, with_slots=with_eid,
                            hub_cap=suggest_hub_cap(
                                int(cur.shape[0]), hub_fracs.get(et))))
                    else:
                        nbrs, slots = unpack(sample_layer(
                            indptr, indices, cur, k, sub,
                            with_slots=with_eid))
                    if slots is not None and et in eids:
                        # CSR slot -> original COO edge id (CSRTopo.eid)
                        e = eids[et]
                        slots = jnp.where(
                            slots >= 0,
                            e[jnp.clip(slots, 0, e.shape[0] - 1)]
                            .astype(slots.dtype), -1)
                    per_rel_samples[et] = (cur, nbrs, slots)
                # 2. per src type: compact (old frontier ++ all sampled)
                new_frontier = dict(frontier)
                new_counts = {}
                adjs = {}
                by_src: Dict[str, list] = {}
                for et, (cur, nbrs, slots) in per_rel_samples.items():
                    by_src.setdefault(et[0], []).append(
                        (et, cur, nbrs, slots))
                for src_t, group in by_src.items():
                    prev = frontier[src_t]
                    prev = prev if prev is not None else \
                        jnp.full((0,), -1, jnp.int32)
                    all_nbrs = jnp.concatenate(
                        [nbrs.reshape(-1) for _, _, nbrs, _ in group])
                    n_id, n_count, extra_local = compact_union(prev, all_nbrs)
                    cap = caps.get(src_t) if caps else None
                    if cap is not None and n_id.shape[0] > cap:
                        # static-capacity truncation: keep the seeds-
                        # first prefix, mask edges whose source fell
                        # past the cap (same -1 contract as everywhere)
                        n_id = n_id[:cap]
                        n_count = jnp.minimum(n_count, cap)
                        extra_local = jnp.where(
                            extra_local < cap, extra_local, -1)
                    # n_id holds prev ++ unique new, first-occurrence order
                    new_frontier[src_t] = n_id
                    new_counts[src_t] = n_count
                    # 3. per relation: local COO against the merged frontier
                    offset = 0
                    for et, cur, nbrs, slots in group:
                        s, kk = nbrs.shape
                        flat = extra_local[offset:offset + s * kk]
                        offset += s * kk
                        row = jnp.where(
                            flat >= 0,
                            jnp.repeat(jnp.arange(s, dtype=jnp.int32), kk),
                            -1)
                        edge_index = jnp.stack([flat, row])
                        e_id = None
                        if slots is not None:
                            # frontier-cap truncation masks the edge in
                            # flat; its e_id masks with it
                            e_id = jnp.where(flat >= 0,
                                             slots.reshape(-1), -1)
                        adjs[et] = Adj(
                            edge_index=edge_index, e_id=e_id,
                            size=(int(n_id.shape[0]), s),
                            mask=flat >= 0)
                hops.append((adjs, dict(new_frontier), new_counts))
                frontier = new_frontier
            return frontier, hops

        return jax.jit(run)

    def sample(self, seeds):
        seeds = jnp.asarray(seeds, jnp.int32)
        bs = int(seeds.shape[0])
        if self.frontier_cap is not None and \
                self.frontier_cap.get(self.seed_type, bs) < bs:
            raise ValueError(
                f"frontier_cap[{self.seed_type!r}] = "
                f"{self.frontier_cap[self.seed_type]} < batch size {bs}: "
                "the cap would truncate the seeds themselves")
        if self._rows is None:
            if self.sampling in ("rotation", "window"):
                self.reshuffle()
            elif self.wide_exact:
                # exact: static layout views of the un-shuffled indices
                # route every relation through the wide-fetch exact path
                # (weighted relations draw from the pool CDF instead —
                # no view, no +E copy for them)
                self._rows = {et: self._as_rows(jnp.asarray(t.indices))
                              for et, t in self.topo.rels.items()
                              if not (self.edge_weight
                                      and et in self.edge_weight)}
                # one cached degree-bucket split per relation sizes the
                # static hub budget (CSRTopo caches it, so a topology
                # shared by several samplers computes it once)
                self._hub_fracs = {
                    et: float(self.topo.rels[et]
                              .exact_bucket_meta(step=128).frac)
                    for et in self._rows}
        if self._rels_placed is None:
            self._rels_placed = {
                et: (jnp.asarray(t.indptr), jnp.asarray(t.indices))
                for et, t in self.topo.rels.items()}
        if self.edge_weight is not None and self._weights_placed is None:
            self._weights_placed = {et: jnp.asarray(w)
                                    for et, w in self.edge_weight.items()}
        if self.with_eid and self.sampling == "exact" \
                and self._eids_placed is None:
            # rotation/window never read these (they use _rot_eids);
            # building them there would place E-sized arrays for nothing
            self._eids_placed = {
                et: jnp.asarray(t.eid)
                for et, t in self.topo.rels.items() if t.eid is not None}
        # rotation/window slots live in permuted coordinates: map them
        # through the co-permuted per-relation maps instead of the raw
        # topo eids
        eids_arg = (self._rot_eids
                    if self.sampling in ("rotation", "window")
                    else self._eids_placed)
        fn = self._fn_cache.get(bs)
        if fn is None:
            fn = self._build(bs)
            self._fn_cache[bs] = fn
        frontier, hops = fn(seeds, self.next_key(), self._rows,
                            self._rels_placed,
                            self._weights_placed or {},
                            eids_arg or {})
        layers = [HeteroLayer(adjs=a, frontier=f, counts=c)
                  for a, f, c in hops]
        return frontier, bs, layers[::-1]
