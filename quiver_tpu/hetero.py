"""Heterogeneous graph support: typed topology + relational k-hop sampler.

Covers the MAG240M-class workload (BASELINE configs[3]; reference
benchmarks/ogbn-mag240m). The reference trains on the homogeneous
paper-cites-paper projection (train_quiver_multi_node.py:90-93) — this
module supports that *and* true multi-relation sampling for R-GCN:

- ``HeteroCSRTopo``: one CSR per relation (src_type, rel, dst_type), each
  an ordinary ``CSRTopo`` over the dst-type id space with src-type ids as
  indices (CSR rows = dst nodes, matching the sampling direction:
  frontier nodes pull their in-neighbors).
- ``HeteroGraphSageSampler``: per hop, every relation samples ``k`` of
  the current dst-type frontier's neighbors; per node type, the frontier
  union is compacted with the same first-occurrence static-shape
  compaction as the homogeneous path.

All shapes static; same -1 masking contract as the homogeneous sampler.
"""

from __future__ import annotations

from typing import Dict, List, NamedTuple, Sequence, Tuple

import jax
import jax.numpy as jnp

from .ops.sample import compact_union, sample_layer
from .pyg.sage_sampler import Adj
from .utils import CSRTopo

EdgeType = Tuple[str, str, str]          # (src_type, relation, dst_type)


class HeteroCSRTopo:
    """Typed topology: ``rels[(src, rel, dst)] = CSRTopo`` whose row v
    (a dst-type node) lists its src-type in-neighbors."""

    def __init__(self, rels: Dict[EdgeType, CSRTopo],
                 node_counts: Dict[str, int]):
        self.rels = dict(rels)
        self.node_counts = dict(node_counts)
        for (src, rel, dst), topo in self.rels.items():
            if topo.node_count < self.node_counts.get(dst, 0):
                raise ValueError(
                    f"relation {(src, rel, dst)} CSR has {topo.node_count} "
                    f"rows < dst node_count {self.node_counts[dst]}")

    @property
    def edge_types(self) -> List[EdgeType]:
        return list(self.rels.keys())

    @property
    def node_types(self) -> List[str]:
        return list(self.node_counts.keys())


class HeteroLayer(NamedTuple):
    """One sampled hop of a hetero graph.

    adjs:     {edge_type: Adj} — local bipartite COO per relation; source
              local ids index the *next* frontier of the src type, target
              local ids index the current frontier of the dst type.
    frontier: {node_type: n_id array} AFTER this hop (input to next hop /
              feature gather), -1-filled static caps.
    counts:   {node_type: valid count in frontier}
    """

    adjs: Dict[EdgeType, Adj]
    frontier: Dict[str, jax.Array]
    counts: Dict[str, jax.Array]


class HeteroGraphSageSampler:
    """Relational neighbor sampler.

    ``sizes`` is a list of per-hop fanouts; each entry is either an int
    (same fanout for every relation) or a ``{edge_type: k}`` dict.
    ``sample(seeds)`` seeds are nodes of ``seed_type``.
    """

    def __init__(self, topo: HeteroCSRTopo, sizes: Sequence,
                 seed_type: str, seed: int = 0):
        self.topo = topo
        self.seed_type = seed_type
        self.sizes = [s if isinstance(s, dict)
                      else {et: s for et in topo.edge_types}
                      for s in sizes]
        self._key = jax.random.key(seed)
        self._fn_cache = {}

    def next_key(self):
        self._key, sub = jax.random.split(self._key)
        return sub

    def _build(self, batch_size: int):
        sizes = self.sizes
        rels = {et: (jnp.asarray(t.indptr), jnp.asarray(t.indices))
                for et, t in self.topo.rels.items()}
        seed_type = self.seed_type
        node_types = self.topo.node_types

        def run(seeds, key):
            frontier = {t: None for t in node_types}
            frontier[seed_type] = seeds.astype(jnp.int32)
            hops = []
            step = 0
            for hop, fanouts in enumerate(sizes):
                per_rel_samples: Dict[EdgeType, tuple] = {}
                # 1. sample every relation whose dst type has a frontier
                for et, k in fanouts.items():
                    src_t, _, dst_t = et
                    cur = frontier[dst_t]
                    if cur is None or k <= 0:
                        continue
                    sub = jax.random.fold_in(key, step)
                    step += 1
                    indptr, indices = rels[et]
                    nbrs, _ = sample_layer(indptr, indices, cur, k, sub)
                    per_rel_samples[et] = (cur, nbrs)
                # 2. per src type: compact (old frontier ++ all sampled)
                new_frontier = dict(frontier)
                new_counts = {}
                adjs = {}
                by_src: Dict[str, list] = {}
                for et, (cur, nbrs) in per_rel_samples.items():
                    by_src.setdefault(et[0], []).append((et, cur, nbrs))
                for src_t, group in by_src.items():
                    prev = frontier[src_t]
                    prev = prev if prev is not None else \
                        jnp.full((0,), -1, jnp.int32)
                    all_nbrs = jnp.concatenate(
                        [nbrs.reshape(-1) for _, _, nbrs in group])
                    n_id, n_count, extra_local = compact_union(prev, all_nbrs)
                    # n_id holds prev ++ unique new, first-occurrence order
                    new_frontier[src_t] = n_id
                    new_counts[src_t] = n_count
                    # 3. per relation: local COO against the merged frontier
                    offset = 0
                    for et, cur, nbrs in group:
                        s, kk = nbrs.shape
                        flat = extra_local[offset:offset + s * kk]
                        offset += s * kk
                        row = jnp.where(
                            flat >= 0,
                            jnp.repeat(jnp.arange(s, dtype=jnp.int32), kk),
                            -1)
                        edge_index = jnp.stack([flat, row])
                        adjs[et] = Adj(
                            edge_index=edge_index, e_id=None,
                            size=(int(n_id.shape[0]), s),
                            mask=flat >= 0)
                hops.append((adjs, dict(new_frontier), new_counts))
                frontier = new_frontier
            return frontier, hops

        return jax.jit(run)

    def sample(self, seeds):
        seeds = jnp.asarray(seeds, jnp.int32)
        bs = int(seeds.shape[0])
        fn = self._fn_cache.get(bs)
        if fn is None:
            fn = self._build(bs)
            self._fn_cache[bs] = fn
        frontier, hops = fn(seeds, self.next_key())
        layers = [HeteroLayer(adjs=a, frontier=f, counts=c)
                  for a, f, c in hops]
        return frontier, bs, layers[::-1]
