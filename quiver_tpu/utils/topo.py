"""Device interconnect topology.

TPU-native replacement for the reference's P2P clique discovery
(``Topo``/``find_cliques``/``color_mat``, utils.py:8-107, and the CUDA
``init_p2p``/``can_device_access_peer`` probe, quiver_feature.cu:363-413).

On TPU there is nothing to probe: every chip within a slice is connected by
ICI (the generalization of an NVLink clique), and slices are joined by DCN.
A "clique" is therefore a slice; peer access inside it is always true. The
class keeps the reference's query API (``get_clique_id``, ``info``,
``p2p_clique``) so user code ports over unchanged.
"""

from __future__ import annotations

from typing import List, Optional, Sequence

import jax


def _slice_key(device) -> tuple:
    return (device.process_index, getattr(device, "slice_index", 0))


class Topo:
    """ICI clique topology over a list of jax devices (defaults to all)."""

    def __init__(self, device_list: Optional[Sequence] = None):
        if device_list is None:
            devices = list(jax.devices())
        elif device_list and isinstance(device_list[0], int):
            all_devices = jax.devices()
            devices = [all_devices[i] for i in device_list]
        else:
            devices = list(device_list)
        self.devices = devices
        groups = {}
        for d in devices:
            groups.setdefault(_slice_key(d), []).append(d)
        self.cliques: List[List] = list(groups.values())
        self._clique_of = {}
        for cid, clique in enumerate(self.cliques):
            for d in clique:
                self._clique_of[d.id] = cid

    @property
    def Topo_Dict(self):
        return {cid: [d.id for d in c] for cid, c in enumerate(self.cliques)}

    def get_clique_id(self, device) -> int:
        device_id = device if isinstance(device, int) else device.id
        return self._clique_of[device_id]

    def p2p_clique(self, clique_id: int) -> List[int]:
        return [d.id for d in self.cliques[clique_id]]

    def info(self) -> str:
        lines = ["ICI topology:"]
        for cid, clique in enumerate(self.cliques):
            ids = ", ".join(str(d.id) for d in clique)
            lines.append(f"  clique {cid} (ICI-connected): devices [{ids}]")
        out = "\n".join(lines)
        print(out)
        return out


def init_p2p(device_list: Optional[Sequence[int]] = None) -> Topo:
    """API-compat shim for the reference ``quiver.init_p2p`` (utils.py:251-257).

    TPU ICI links need no enabling; this just returns the discovered
    topology so callers can inspect cliques.
    """
    return Topo(device_list)


def can_device_access_peer(src: int, dst: int) -> bool:
    topo = Topo()
    try:
        return topo.get_clique_id(src) == topo.get_clique_id(dst)
    except KeyError:
        return False
