"""CSR graph topology container.

TPU-native equivalent of the reference ``CSRTopo`` (utils.py:120-226) and
``get_csr_from_coo`` (utils.py:110-117). Differences by design:

- arrays are jnp (device-resident) pytree leaves, not torch CPU tensors;
  COO->CSR runs on-device via stable argsort + searchsorted (no scipy).
- node ids default to int32 (TPU-preferred); ``indptr`` widens to int64
  only when edge_count >= 2**31 (mixed-width CSR, survey §7.3.7). In
  jax's default 32-bit mode such a topology stays HOST-RESIDENT (numpy;
  memmaps pass through zero-copy) because jnp would silently truncate
  the offsets — the HOST/CPU sampling paths consume it directly.
- isolated tail nodes are kept when ``node_count`` is passed explicitly
  (the reference silently drops them, a known quirk — survey §7.4).
"""

from __future__ import annotations

from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np

INT32_MAX = np.iinfo(np.int32).max


def index_dtype_for(count: int):
    """Smallest TPU-friendly integer dtype that can index ``count`` items."""
    return jnp.int32 if count <= INT32_MAX else jnp.int64


def _as_jnp(x, dtype=None):
    if x is None:
        return None
    arr = jnp.asarray(x)
    if dtype is not None and arr.dtype != dtype:
        arr = arr.astype(dtype)
    return arr


def get_csr_from_coo(edge_index, node_count: Optional[int] = None):
    """COO ``edge_index`` (2, E) -> (indptr, indices, eid).

    ``eid[j]`` is the original COO position of the edge stored at CSR slot
    ``j`` (the reference keeps the same mapping via scipy's csr ``.data``).
    """
    edge_index = jnp.asarray(edge_index)
    row, col = edge_index[0], edge_index[1]
    e = int(row.shape[0])
    if node_count is None:
        if e == 0:
            node_count = 0
        else:
            node_count = int(jnp.maximum(row.max(), col.max())) + 1
    node_dtype = index_dtype_for(max(node_count, 1))
    ptr_dtype = index_dtype_for(max(e, 1))

    order = jnp.argsort(row, stable=True)
    indices = col[order].astype(node_dtype)
    eid = order.astype(ptr_dtype)
    row_sorted = row[order]
    indptr = jnp.searchsorted(
        row_sorted, jnp.arange(node_count + 1, dtype=row_sorted.dtype)
    ).astype(ptr_dtype)
    return indptr, indices, eid


@jax.tree_util.register_pytree_node_class
class CSRTopo:
    """Canonical graph topology: CSR ``indptr``/``indices`` (+ optional
    ``eid`` edge-id map and ``feature_order`` hot-cache permutation).

    Mirrors the API of the reference ``CSRTopo`` (utils.py:120-226):
    ``indptr``/``indices``/``eid``/``feature_order`` properties, ``degree``,
    ``node_count``, ``edge_count``. ``share_memory_`` is a no-op on TPU
    (one process owns all local chips; no cross-process IPC needed).
    """

    def __init__(self, edge_index=None, indptr=None, indices=None, eid=None,
                 node_count: Optional[int] = None):
        if edge_index is not None:
            self._indptr, self._indices, self._eid = get_csr_from_coo(
                edge_index, node_count)
        elif indptr is not None and indices is not None:
            e = int(np.asarray(jnp.shape(indices))[0]) if hasattr(indices, "shape") else len(indices)
            ptr_dtype = index_dtype_for(max(e, 1))
            if ptr_dtype == jnp.int64 and not jax.config.jax_enable_x64:
                # >2^31 edge offsets but jax is in default 32-bit mode:
                # jnp.asarray would SILENTLY truncate indptr to int32.
                # Keep the topology host-resident as numpy (memmaps pass
                # through zero-copy) — at this scale sampling runs on the
                # HOST/CPU paths anyway (the reference equally keeps
                # papers100M topology out of device memory via UVA,
                # quiver_sample.cu:412-453).
                n = (indptr.shape[0] if hasattr(indptr, "shape")
                     else len(indptr)) - 1
                self._indptr = np.ascontiguousarray(indptr, dtype=np.int64)
                self._indices = np.ascontiguousarray(
                    indices, dtype=np.int32 if n <= INT32_MAX else np.int64)
                self._eid = (None if eid is None
                             else np.ascontiguousarray(eid, dtype=np.int64))
            else:
                self._indptr = _as_jnp(indptr, ptr_dtype)
                n = int(self._indptr.shape[0]) - 1
                self._indices = _as_jnp(indices, index_dtype_for(max(n, 1)))
                self._eid = _as_jnp(eid, ptr_dtype)
        else:
            raise ValueError("provide either edge_index or indptr+indices")
        self._feature_order = None
        self._bucket_meta = {}   # {step: ExactBucketMeta}, lazy

    # -- pytree protocol ----------------------------------------------------
    def tree_flatten(self):
        leaves = (self._indptr, self._indices, self._eid, self._feature_order)
        return leaves, None

    @classmethod
    def tree_unflatten(cls, aux, leaves):
        obj = cls.__new__(cls)
        obj._indptr, obj._indices, obj._eid, obj._feature_order = leaves
        obj._bucket_meta = {}
        return obj

    # -- accessors ----------------------------------------------------------
    @property
    def indptr(self):
        return self._indptr

    @property
    def indices(self):
        return self._indices

    @property
    def eid(self):
        return self._eid

    @property
    def feature_order(self):
        return self._feature_order

    @feature_order.setter
    def feature_order(self, order):
        self._feature_order = None if order is None else jnp.asarray(order)

    @property
    def degree(self):
        return self._indptr[1:] - self._indptr[:-1]

    @property
    def node_count(self) -> int:
        return int(self._indptr.shape[0]) - 1

    @property
    def edge_count(self) -> int:
        return int(self._indices.shape[0])

    def exact_bucket_meta(self, step: int = 128):
        """Degree-bucket split for the wide-fetch exact sampler
        (``ops.sample.ExactBucketMeta``): hub-mass fractions that size
        the static scattered-load budget (``suggest_hub_cap``). Computed
        once per row-layout ``step`` and cached — the homogeneous
        sampler, every hetero relation, and the fused train step all
        read the same cached split, so the multi-hop program's shapes
        are decided once per graph, not per epoch."""
        meta = self._bucket_meta.get(step)
        if meta is None:
            from ..ops.sample import exact_bucket_meta
            meta = exact_bucket_meta(self._indptr, step=step)
            self._bucket_meta[step] = meta
        return meta

    def share_memory_(self):
        return self

    def requires_host_sampling(self) -> bool:
        """True when the topology's offsets exceed int32 and jax is in
        default 32-bit mode — the arrays must stay host-side numpy
        (device placement would silently wrap the offsets)."""
        return (isinstance(self._indptr, np.ndarray)
                and self._indptr.dtype == np.int64
                and not jax.config.jax_enable_x64)

    def device_put(self, sharding_or_device=None):
        """Place topology arrays (HBM by default; pass a Sharding with
        ``memory_kind='pinned_host'`` for the host/zero-copy tier)."""
        if self.requires_host_sampling():
            raise ValueError(
                "this topology's edge offsets exceed int32 and jax is in "
                "default 32-bit mode: jax.device_put would silently wrap "
                "them. Keep it host-resident (mode='CPU' sampling) or "
                "enable jax_enable_x64.")
        put = lambda x: None if x is None else jax.device_put(x, sharding_or_device)
        obj = CSRTopo.__new__(CSRTopo)
        obj._indptr = put(self._indptr)
        obj._indices = put(self._indices)
        obj._eid = put(self._eid)
        obj._feature_order = put(self._feature_order)
        obj._bucket_meta = dict(self._bucket_meta)  # placement-independent
        return obj

    def __repr__(self):
        return (f"CSRTopo(node_count={self.node_count}, "
                f"edge_count={self.edge_count}, "
                f"indptr_dtype={self._indptr.dtype}, "
                f"indices_dtype={self._indices.dtype})")
