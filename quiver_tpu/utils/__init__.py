from .csr import CSRTopo, get_csr_from_coo, index_dtype_for
from .sizes import parse_size, UNITS
from .reorder import reindex_by_config, reindex_feature
from .topo import Topo, init_p2p

__all__ = [
    "CSRTopo",
    "get_csr_from_coo",
    "index_dtype_for",
    "parse_size",
    "UNITS",
    "reindex_by_config",
    "reindex_feature",
    "Topo",
    "init_p2p",
]
