"""Degree-descending hot-order reindexing for cache placement.

Capability parity with the reference ``reindex_by_config``/``reindex_feature``
(utils.py:230-248): sort nodes by degree descending, randomly shuffle the
cached (hot) prefix for load balance, and return the permuted feature plus
the ``new_order`` map (old id -> new row).

Host-side preprocessing: runs in numpy (feature tensors may exceed HBM at
this stage; the permuted result is what gets placed on device).
"""

from __future__ import annotations

import numpy as np


def reindex_by_config(adj_csr, graph_feature, gpu_portion: float, seed: int = 0):
    """Returns (permuted_feature, new_order).

    ``prev_order[i]`` = old node id stored at new row i (degree-descending,
    hot prefix shuffled). ``new_order[old_id]`` = new row of ``old_id``.
    """
    degree = np.asarray(adj_csr.degree)
    node_count = degree.shape[0]
    prev_order = np.argsort(-degree, kind="stable")
    hot = int(node_count * gpu_portion)
    rng = np.random.default_rng(seed)
    perm = rng.permutation(hot)
    prev_order[:hot] = prev_order[perm]
    new_order = np.empty(node_count, dtype=np.int64)
    new_order[prev_order] = np.arange(node_count, dtype=np.int64)
    feature = None
    if graph_feature is not None:
        feature = np.asarray(graph_feature)[prev_order]
    return feature, new_order


def reindex_feature(graph: "CSRTopo", feature, ratio: float, seed: int = 0):
    feature, new_order = reindex_by_config(graph, feature, ratio, seed=seed)
    return feature, new_order
