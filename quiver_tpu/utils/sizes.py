"""Human-readable size parsing ("200M", "4GB") -> bytes.

Capability parity with the reference ``parse_size`` (utils.py:260-281).
"""

import re

KILO = 1024

UNITS = {
    "KB": KILO,
    "MB": KILO ** 2,
    "GB": KILO ** 3,
    "TB": KILO ** 4,
    "K": KILO,
    "M": KILO ** 2,
    "G": KILO ** 3,
    "T": KILO ** 4,
}

_SIZE_RE = re.compile(r"^\s*([0-9]*\.?[0-9]+)\s*([A-Za-z]*)\s*$")


def parse_size(size) -> int:
    if isinstance(size, (int, float)):
        return int(size)
    if not isinstance(size, str):
        raise ValueError(f"cannot parse size: {size!r}")
    m = _SIZE_RE.match(size)
    if not m:
        raise ValueError(f"cannot parse size: {size!r}")
    value, unit = m.groups()
    if not unit:
        return int(float(value))
    unit = unit.upper()
    if unit not in UNITS:
        raise ValueError(f"unknown size unit {unit!r} in {size!r}")
    return int(float(value) * UNITS[unit])
