"""Host-offload (pinned host memory) placement with loud fallback.

Shared by the sampler's HOST mode and the Feature store's offload host
tier. A silently different performance regime is the failure mode the
reference guards with its CUDA check macros (quiver.cu.hpp:16-26), so
backends without usable host-offload either warn via the package
logger (allow_fallback=True) or raise.
"""

from __future__ import annotations

import jax

from ..debug import log as _log

# (platform, mesh?) -> bool; a capability PROBE, not a platform
# allowlist: the failure mode being guarded (today's CPU backend
# ACCEPTS the pinned_host placement and then fails compiling any op
# mixing host- and default-space operands — placement succeeds, every
# later use raises) is a property of the installed jax/backend pair,
# so it is probed with a tiny mixed-space op instead of hardcoding a
# platform string that would silently force the fallback regime on a
# future jax where CPU host-offload works. Probed per sharding FORM
# (single-device vs mesh NamedSharding) because the two can differ.
_USABLE: dict = {}


def _definitive(e: Exception) -> bool:
    """True when the failure is the compile/placement capability gap
    itself (cacheable), not a transient backend error that would
    otherwise lock a long-lived process into the fallback regime."""
    msg = str(e).lower()
    return isinstance(e, NotImplementedError) or \
        "memory_space" in msg or "memory kind" in msg or \
        "memory_kind" in msg or "pinned_host" in msg


def _host_offload_usable(dev, mesh=None) -> bool:
    key = (getattr(dev, "platform", None), mesh is not None)
    got = _USABLE.get(key)
    if got is None:
        import numpy as np
        try:
            if mesh is not None:
                sh = jax.sharding.NamedSharding(
                    mesh, jax.sharding.PartitionSpec(),
                    memory_kind="pinned_host")
                main_sh = jax.sharding.NamedSharding(
                    mesh, jax.sharding.PartitionSpec())
            else:
                sh = jax.sharding.SingleDeviceSharding(
                    dev, memory_kind="pinned_host")
                main_sh = dev
            host = jax.device_put(np.ones((8,), np.float32), sh)
            main = jax.device_put(np.ones((8,), np.float32), main_sh)
            # the exact usage pattern the offload tiers need: one jitted
            # computation over a host-space and a default-space operand
            float(jax.jit(lambda h, m: (h + m).sum())(host, main))
            got = True
        except Exception as e:  # noqa: BLE001 - classify, maybe cache
            if not _definitive(e):
                return False    # transient: fail this call, don't cache
            got = False
        _USABLE[key] = got
    return got


def pinned_put(arrays, dev, allow_fallback, what, mesh=None):
    """Place ``arrays`` on pinned host memory. Returns the placed list,
    or None after a LOUD log when ``allow_fallback`` and the placement
    is unusable; raises otherwise.

    With ``mesh`` the arrays are placed host-replicated over the mesh
    (``NamedSharding(mesh, P(), memory_kind='pinned_host')``) so they
    can feed computations whose other operands are mesh-sharded —
    single-device pinned arrays and mesh-sharded arrays have
    incompatible device sets and fail at dispatch.

    Usability is established by ``_host_offload_usable``'s probe (one
    tiny mixed-memory-space op per platform, cached); the TPU side is
    additionally measured on chip by benchmarks/host_mode_probe.py."""
    try:
        probe_dev = mesh.devices.flat[0] if mesh is not None else dev
        if not _host_offload_usable(probe_dev, mesh=mesh):
            raise NotImplementedError(
                "this backend accepts pinned_host placement but cannot "
                "compile mixed-memory-space ops (probed)")
        if mesh is not None:
            sh = jax.sharding.NamedSharding(
                mesh, jax.sharding.PartitionSpec(),
                memory_kind="pinned_host")
        else:
            sh = jax.sharding.SingleDeviceSharding(
                dev, memory_kind="pinned_host")
        return [jax.device_put(a, sh) for a in arrays]
    except (ValueError, NotImplementedError) as e:
        if not allow_fallback:
            raise ValueError(
                "no usable 'pinned_host' memory kind here "
                f"(placing {what}): {e}. Default placement is a "
                "different performance regime — pass allow_fallback="
                "True to accept it") from e
        _log("no usable 'pinned_host' memory kind on this backend; "
             "%s falls back to default placement (a different "
             "performance regime)", what)
        return None
