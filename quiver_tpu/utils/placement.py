"""Host-offload (pinned host memory) placement with loud fallback.

Shared by the sampler's HOST mode and the Feature store's offload host
tier. A silently different performance regime is the failure mode the
reference guards with its CUDA check macros (quiver.cu.hpp:16-26), so
backends without usable host-offload either warn via the package
logger (allow_fallback=True) or raise.
"""

from __future__ import annotations

import jax

from ..debug import log as _log


def pinned_put(arrays, dev, allow_fallback, what, mesh=None):
    """Place ``arrays`` on pinned host memory. Returns the placed list,
    or None after a LOUD log when ``allow_fallback`` and the placement
    is unusable; raises otherwise.

    With ``mesh`` the arrays are placed host-replicated over the mesh
    (``NamedSharding(mesh, P(), memory_kind='pinned_host')``) so they
    can feed computations whose other operands are mesh-sharded —
    single-device pinned arrays and mesh-sharded arrays have
    incompatible device sets and fail at dispatch.

    The CPU backend is explicitly gated out: it ACCEPTS the
    ``pinned_host`` placement and then fails at compile time on any
    computation mixing host- and default-space operands — the worst of
    both: placement succeeds, every later use raises. TPU/GPU backends
    pass through (the TPU side is probed on chip by
    benchmarks/host_mode_probe.py)."""
    try:
        platform = (mesh.devices.flat[0].platform if mesh is not None
                    else getattr(dev, "platform", None))
        if platform == "cpu":
            raise NotImplementedError(
                "the CPU backend accepts pinned_host placement and then "
                "fails compiling mixed-memory-space ops")
        if mesh is not None:
            sh = jax.sharding.NamedSharding(
                mesh, jax.sharding.PartitionSpec(),
                memory_kind="pinned_host")
        else:
            sh = jax.sharding.SingleDeviceSharding(
                dev, memory_kind="pinned_host")
        return [jax.device_put(a, sh) for a in arrays]
    except (ValueError, NotImplementedError) as e:
        if not allow_fallback:
            raise ValueError(
                "no usable 'pinned_host' memory kind here "
                f"(placing {what}): {e}. Default placement is a "
                "different performance regime — pass allow_fallback="
                "True to accept it") from e
        _log("no usable 'pinned_host' memory kind on this backend; "
             "%s falls back to default placement (a different "
             "performance regime)", what)
        return None
