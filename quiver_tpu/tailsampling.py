"""Tail-based trace sampling + fleet trace assembly (qt-tail).

PR 7's tracer is opt-in, full-capture and single-process: fine for a
debugging session, exactly wrong for production — at the 2010.03166
scalability regime you cannot keep every span of every request, and
the request you NEED is the one that just burned the p99 budget at
3am. Production systems solve this with **tail-based sampling**:
buffer every request's spans cheaply while the request is in flight,
and decide keep-vs-drop only when the *outcome* is known — an error,
a blown deadline, a p99-busting latency, an active anomaly window —
plus a small probabilistic floor so the healthy baseline stays
represented. Kept traces emit as ``trace`` JSONL records through the
existing ``MetricsSink`` plumbing, which makes the fleet plane's
aggregator a trace *assembler* for free: the PR-13 global
``trace_id`` stitches a client's RPC spans and a replica's serve
spans into one cross-process record.

Three pieces:

- :class:`TailSampler` — attaches to a ``tracing.Tracer``
  (:meth:`attach` — every recorded span is offered to it). Spans
  accumulate per ``trace_id`` in a BOUNDED pending-trace table
  (``max_pending`` entries, LRU-evicting the oldest incomplete trace;
  evictions and per-trace span truncation are COUNTED, never silent —
  memory is bounded by construction no matter the in-flight load).
  A trace completes when its ROOT span arrives (``serve.request`` on
  a replica, ``rpc.lookup`` on a client); completion runs the policy
  chain (:data:`TAIL_POLICY_NAMES`, first match keeps):

  | policy | keeps when |
  |---|---|
  | ``error`` | any span carries an ``error`` arg other than a deadline |
  | ``deadline_exceeded`` | any span's ``error`` is ``DeadlineExceeded`` |
  | ``latency_over_p99`` | the root span's duration exceeds the live threshold (``latency_source`` — an SLO target or the observed request p99, see :func:`latency_source_from`) |
  | ``anomaly_window`` | the trace completed inside an armed anomaly window (:meth:`TailSampler.arm_anomaly_window`, wired to ``TelemetryHub`` detector firings via :meth:`watch_hub`) |
  | ``head_sample`` | the seeded probabilistic floor (``head_rate``) |

  Everything else drops. Batch-scoped spans (``serve.batch_coalesce``
  / ``serve.dispatch`` / ``serve.scatter`` — their ``trace_id`` is a
  batch id that never completes) live in a separate small LRU buffer
  and are MERGED into a kept request trace through the root span's
  ``batch`` arg, so a kept trace shows its batch's dispatch timeline
  without batch ids ever occupying (or thrashing) the pending table.

- **assembly** — :class:`TraceStore` groups ``trace`` records by
  ``trace_id`` across sources (the fleet aggregator feeds it one
  source per replica sink) and :func:`assemble` merges the segments:
  per-segment critical path plus the cross-segment dominant span and
  the queue-vs-execute split (the profile vocabulary: *queue* =
  admission/coalesce/pipeline waits + rpc backoff, *execute* =
  dispatch/pipeline execute + rpc attempts). Per-process span
  timestamps are ``perf_counter``-relative and fleet clocks disagree,
  so segments keep their own time bases — correlation is by
  ``trace_id``, never by wall clock.

- **exemplars** — ``fleet.prometheus_text`` stamps OpenMetrics
  exemplar syntax (``... # {trace_id="..."} <duration_ms>``) on
  latency series, pointing each bad number at the newest kept trace
  that explains it: burn alert → exemplar → ``scripts/qt_trace.py
  --trace-id`` → the critical path.

Stdlib only — no jax, no numpy: jax-free replica/client processes
(and ``scripts/qt_trace.py``) load this file through a synthetic
package in milliseconds, and nothing here can enter a jitted program
(the zero-host-sync pins hold by construction; ``check_leak`` phase
12 measures it anyway). The sampler never emits under its own lock
(the ``lock_held_emit`` host-lint contract) and its per-span cost is
one dict append under one lock — ``bench_serving.py``'s ``tail_ab``
block pins the always-on arm within noise of detached.

Usage::

    from quiver_tpu import tailsampling, tracing
    sampler = tailsampling.TailSampler(
        sink=sink, latency_source=lambda: 100.0, head_rate=0.001)
    sampler.attach()              # enables tracing + hooks the tracer
    ...                           # serve traffic; kept traces -> sink
    sampler.stats()               # kept/dropped/evicted/high-water
    sampler.detach()
"""

from __future__ import annotations

import collections
import random
import threading
import time
from typing import Callable, Dict, List, Optional, Sequence, Tuple

from . import tracing

__all__ = ["TAIL_POLICY_NAMES", "TailSampler", "TraceStore", "assemble",
           "critical_path", "latency_source_from",
           "trace_record_to_chrome_events"]

#: the keep policies, in evaluation order (first match wins); the
#: lint.sh drift check pins a backticked row per name in
#: docs/observability.md
TAIL_POLICY_NAMES = ("error", "deadline_exceeded", "latency_over_p99",
                     "anomaly_window", "head_sample")

#: span names that COMPLETE a trace (the request's terminal span on
#: each side of the wire)
DEFAULT_ROOT_SPANS = ("serve.request", "rpc.lookup")

#: batch-scoped span names: their trace_id is a serving BATCH id (the
#: ``batch`` arg request spans carry), buffered separately and merged
#: into kept request traces — never pending-table entries
BATCH_SPAN_NAMES = ("serve.batch_coalesce", "serve.dispatch",
                    "serve.scatter")

#: the queue-vs-execute split vocabulary (the profile/costmodel
#: framing: time spent WAITING vs time spent DOING)
QUEUE_SPAN_NAMES = ("serve.admission_wait", "serve.coalesce_wait",
                    "pipeline.queue_wait", "rpc.backoff")
EXECUTE_SPAN_NAMES = ("serve.dispatch", "pipeline.execute",
                      "rpc.attempt", "rpc.hedge", "serve.scatter")


def latency_source_from(slo=None, stats=None,
                        floor_ms: float = 0.0) -> Callable[[], Optional[float]]:
    """A ``latency_source`` callable for the ``latency_over_p99``
    policy, fed by the LIVE serving windows: the SLO's latency target
    when a ``metrics.SloBudget`` is armed (the number the burn rate is
    charged against), else the observed per-request p99 from a
    ``metrics.StepStats`` (``request_p99_ms()`` — so "over p99" is
    literal: the trace ran slower than 99% of its recent peers).
    Duck-typed on purpose — this module must stay jax-free."""
    def source() -> Optional[float]:
        if slo is not None:
            return max(float(slo.target_p99_ms), floor_ms)
        if stats is not None:
            p99 = stats.request_p99_ms()
            return None if p99 is None else max(float(p99), floor_ms)
        return None
    return source


class TailSampler:
    """Bounded per-trace span buffer + outcome-driven keep policy.

    - ``sink``: anything with ``emit(record, kind=)`` (a
      ``metrics.MetricsSink``); kept traces emit as kind ``trace``.
    - ``max_pending``: pending-trace table capacity. The table LRU-
      evicts the oldest INCOMPLETE trace when full (``evicted``
      counted); a root span arriving for an evicted trace re-opens it
      with only the spans seen since, so a kept verdict still fires —
      just on a truncated timeline.
    - ``max_spans_per_trace``: per-trace span bound (``truncated_spans``
      counted past it).
    - ``latency_source``: zero-arg callable returning the live
      ``latency_over_p99`` threshold in ms (None disables the policy)
      — see :func:`latency_source_from`.
    - ``head_rate``: the probabilistic head-sampling floor (seeded —
      reproducible).
    - ``anomaly_window_s``: how long :meth:`arm_anomaly_window` keeps
      everything after a detector firing.

    Thread-safe; policy decisions run under the table lock, sink
    emission strictly outside it."""

    def __init__(self, sink=None, max_pending: int = 512,
                 max_spans_per_trace: int = 64,
                 latency_source: Optional[Callable[[], Optional[float]]] = None,
                 head_rate: float = 0.0,
                 anomaly_window_s: float = 30.0,
                 root_spans: Sequence[str] = DEFAULT_ROOT_SPANS,
                 max_batches: int = 64,
                 seed: int = 0, clock=None,
                 on_keep: Optional[Callable[[dict], None]] = None):
        if max_pending < 1:
            raise ValueError(f"max_pending must be >= 1, got {max_pending}")
        if not 0.0 <= float(head_rate) <= 1.0:
            raise ValueError(f"head_rate must be in [0, 1], got {head_rate}")
        self.sink = sink
        self.max_pending = int(max_pending)
        self.max_spans_per_trace = int(max_spans_per_trace)
        self.latency_source = latency_source
        self.head_rate = float(head_rate)
        self.anomaly_window_s = float(anomaly_window_s)
        self.root_spans = tuple(root_spans)
        self.max_batches = int(max_batches)
        self.on_keep = on_keep
        self._clock = clock if clock is not None else time.monotonic
        self._rng = random.Random(seed)
        self._pending: "collections.OrderedDict[int, list]" = \
            collections.OrderedDict()
        self._batches: "collections.OrderedDict[int, list]" = \
            collections.OrderedDict()
        self._anomaly_until = 0.0
        self._lock = threading.Lock()
        self._kept = 0
        self._dropped = 0
        self._evicted = 0
        self._truncated = 0
        self._offered = 0
        self._high_water = 0
        self._by_policy: Dict[str, int] = {}
        self._tracer: Optional[tracing.Tracer] = None

    # -- tracer wiring -------------------------------------------------------
    def attach(self, tracer: Optional[tracing.Tracer] = None) -> "TailSampler":
        """Hook this sampler into ``tracer`` (the process default when
        None) and ENABLE it — always-on tail sampling is "tracing on,
        keep only what the outcome earns"."""
        t = tracer if tracer is not None else tracing.get_tracer()
        t.set_sampler(self)
        t.enable()
        self._tracer = t
        return self

    def detach(self) -> None:
        """Unhook from the tracer (recording stays enabled — the ring
        is the caller's; disable it separately if wanted)."""
        t = self._tracer
        if t is not None and t.sampler() is self:
            t.set_sampler(None)
        self._tracer = None

    # -- the per-span hot path -----------------------------------------------
    def offer(self, name: str, tid: int, t0: float, dur: float,
              trace_id: Optional[int], args: Optional[dict]) -> None:
        """One recorded span (the tracer calls this for every record
        while attached). Spans without a ``trace_id`` are not
        request-scoped and are ignored."""
        if trace_id is None:
            return
        rec = None
        with self._lock:
            self._offered += 1
            if name in BATCH_SPAN_NAMES:
                buf = self._batches.get(trace_id)
                if buf is None:
                    if len(self._batches) >= self.max_batches:
                        self._batches.popitem(last=False)
                    buf = self._batches[trace_id] = []
                buf.append((name, t0, dur, args))
                return
            root = name in self.root_spans
            buf = self._pending.get(trace_id)
            if buf is None and root:
                # root-only completion (the trace was evicted earlier,
                # or its terminal span is its only span): decide on a
                # local buffer WITHOUT occupying the table — inserting
                # just to delete in the same call would evict a LIVE
                # in-flight trace for nothing
                rec = self._decide_locked(
                    trace_id, [(name, t0, dur, args)], name, dur, args)
            else:
                if buf is None:
                    if len(self._pending) >= self.max_pending:
                        # LRU-evict the oldest incomplete trace:
                        # bounded memory beats a complete table; the
                        # loss is COUNTED, never silent
                        self._pending.popitem(last=False)
                        self._evicted += 1
                    buf = self._pending[trace_id] = []
                    if len(self._pending) > self._high_water:
                        self._high_water = len(self._pending)
                else:
                    self._pending.move_to_end(trace_id)
                if len(buf) >= self.max_spans_per_trace and not root:
                    # the ROOT span is exempt: the outcome (error arg,
                    # duration) is the whole basis of the keep decision
                    # — truncating it would silently drop a bad trace
                    self._truncated += 1
                else:
                    buf.append((name, t0, dur, args))
                if root:
                    del self._pending[trace_id]
                    rec = self._decide_locked(trace_id, buf, name,
                                              dur, args)
        # emission strictly OUTSIDE the lock (lock_held_emit): a slow
        # telemetry disk must never stall the serving executor thread
        # that recorded the span
        if rec is not None:
            if self.sink is not None:
                self.sink.emit(rec, kind="trace")
            if self.on_keep is not None:
                try:
                    self.on_keep(rec)
                except Exception:
                    pass

    # -- the policy chain ----------------------------------------------------
    def _decide_locked(self, trace_id: int, spans: list, root_name: str,
                       root_dur: float, root_args) -> Optional[dict]:
        if isinstance(root_args, dict):
            bid = root_args.get("batch")
            if bid is not None and bid in self._batches:
                spans = spans + list(self._batches[bid])
        errors = [a.get("error") for (_n, _t, _d, a) in spans
                  if isinstance(a, dict) and a.get("error")]
        policy = None
        if any(e != "DeadlineExceeded" for e in errors):
            policy = "error"
        elif errors:
            policy = "deadline_exceeded"
        else:
            thr = self.latency_source() if self.latency_source else None
            if thr is not None and root_dur * 1e3 > thr:
                policy = "latency_over_p99"
            elif self._clock() < self._anomaly_until:
                policy = "anomaly_window"
            elif self.head_rate and self._rng.random() < self.head_rate:
                policy = "head_sample"
        if policy is None:
            self._dropped += 1
            return None
        self._kept += 1
        self._by_policy[policy] = self._by_policy.get(policy, 0) + 1
        spans = sorted(spans, key=lambda s: s[1])
        base = spans[0][1] if spans else 0.0
        out_spans = []
        for n, t0, dur, args in spans:
            s = {"name": n, "t0_ms": round((t0 - base) * 1e3, 3),
                 "dur_ms": round(dur * 1e3, 3)}
            if args:
                s["args"] = args
            out_spans.append(s)
        rec = {"trace_id": int(trace_id), "policy": policy,
               "root": root_name,
               "duration_ms": round(root_dur * 1e3, 3),
               "spans": out_spans}
        replica = tracing.get_replica()
        if replica is not None:
            rec["replica"] = replica
        if errors:
            rec["errors"] = errors
        rec.update(critical_path(out_spans, root_name=root_name,
                                 root_dur_ms=root_dur * 1e3))
        return rec

    # -- anomaly window ------------------------------------------------------
    def arm_anomaly_window(self, duration_s: Optional[float] = None) -> None:
        """Keep every trace completing within the window — "what did
        requests look like around the regime shift" is exactly the
        question an anomaly record cannot answer alone."""
        until = self._clock() + (float(duration_s)
                                 if duration_s is not None
                                 else self.anomaly_window_s)
        with self._lock:
            if until > self._anomaly_until:
                self._anomaly_until = until

    def watch_hub(self, hub) -> "TailSampler":
        """Arm the anomaly window from a ``telemetry.TelemetryHub``'s
        detector firings (``hub.on_anomaly`` observers are called
        outside the hub lock)."""
        hub.on_anomaly.append(lambda rec: self.arm_anomaly_window())
        return self

    # -- reading -------------------------------------------------------------
    def stats(self) -> dict:
        with self._lock:
            return {
                "kept": self._kept,
                "dropped": self._dropped,
                "completed": self._kept + self._dropped,
                "evicted": self._evicted,
                "truncated_spans": self._truncated,
                "spans_offered": self._offered,
                "pending": len(self._pending),
                "pending_high_water": self._high_water,
                "pending_capacity": self.max_pending,
                "kept_by_policy": dict(self._by_policy),
            }


# -- critical-path attribution -------------------------------------------------


def critical_path(spans: Sequence[dict], root_name: Optional[str] = None,
                  root_dur_ms: Optional[float] = None) -> dict:
    """Dominant span + queue-vs-execute split over ``{name, dur_ms}``
    span dicts (one kept-trace segment, or an assembled union). The
    dominant span is the longest NON-root span — the single place the
    time went; its ``share`` is of the root duration when known."""
    dominant = None
    queue_ms = 0.0
    execute_ms = 0.0
    for s in spans:
        name = s.get("name")
        dur = float(s.get("dur_ms") or 0.0)
        if name in QUEUE_SPAN_NAMES:
            queue_ms += dur
        elif name in EXECUTE_SPAN_NAMES:
            execute_ms += dur
        if name != root_name and name not in DEFAULT_ROOT_SPANS:
            if dominant is None or dur > dominant["dur_ms"]:
                dominant = {"name": name, "dur_ms": round(dur, 3)}
    if dominant is not None and root_dur_ms:
        dominant["share"] = round(dominant["dur_ms"] / root_dur_ms, 4)
    return {"dominant": dominant,
            "queue_ms": round(queue_ms, 3),
            "execute_ms": round(execute_ms, 3)}


# -- fleet assembly ------------------------------------------------------------


def assemble(trace_id: int, segments: Sequence[dict]) -> dict:
    """Stitch one trace's kept segments (the per-process ``trace``
    records sharing a global ``trace_id``) into the fleet view. Each
    segment keeps its own ``perf_counter`` time base (fleet clocks
    disagree — correlation is by id, never by clock); the assembled
    record carries the cross-segment dominant span, the summed
    queue/execute split, and the end-to-end duration (the client
    segment's root covers the whole remote call, so the max root
    duration is the trace's)."""
    segs = sorted(segments, key=lambda r: (r.get("root") or "",
                                           r.get("replica") or ""))
    all_spans: List[dict] = []
    errors: List[str] = []
    for seg in segs:
        all_spans.extend(seg.get("spans") or ())
        errors.extend(seg.get("errors") or ())
    duration = max((float(s.get("duration_ms") or 0.0) for s in segs),
                   default=0.0)
    out = {
        "trace_id": int(trace_id),
        "segments": list(segs),
        "replicas": sorted({s.get("replica") or "?" for s in segs}),
        "policies": sorted({s.get("policy") or "?" for s in segs}),
        "duration_ms": round(duration, 3),
        "span_count": len(all_spans),
    }
    if errors:
        out["errors"] = errors
    out.update(critical_path(all_spans, root_dur_ms=duration or None))
    return out


class TraceStore:
    """Bounded cross-source store of kept ``trace`` records, grouped
    by ``trace_id`` (LRU over trace ids — the fleet keeps the RECENT
    window). Re-adding the same record is a no-op (the aggregator
    re-reads whole sink files every poll), keyed by ``(source,
    root)`` per trace — a client's ``rpc.lookup`` segment and a
    replica's ``serve.request`` segment coexist even when both land
    in one sink. Thread-safe (the aggregator's poll thread writes
    while exporter scrape threads read)."""

    def __init__(self, capacity: int = 256):
        if capacity < 1:
            raise ValueError(f"capacity must be >= 1, got {capacity}")
        self.capacity = int(capacity)
        self._traces: "collections.OrderedDict[int, dict]" = \
            collections.OrderedDict()
        self._latest: Dict[Optional[str], Tuple[int, float]] = {}
        self._lock = threading.Lock()
        self.added = 0
        self.evicted = 0

    def add(self, rec: dict, source: str = "") -> bool:
        """Fold one ``trace`` record from ``source``; returns True when
        it was new."""
        tid = rec.get("trace_id")
        if tid is None:
            return False
        tid = int(tid)
        key = (str(source), rec.get("root") or "")
        with self._lock:
            ent = self._traces.get(tid)
            if ent is None:
                if len(self._traces) >= self.capacity:
                    self._traces.popitem(last=False)
                    self.evicted += 1
                ent = self._traces[tid] = {}
            else:
                self._traces.move_to_end(tid)
            if key in ent:
                return False
            ent[key] = rec
            self.added += 1
            dur = float(rec.get("duration_ms") or 0.0)
            replica = rec.get("replica") or (str(source) or None)
            self._latest[replica] = (tid, dur)
            self._latest[None] = (tid, dur)
        return True

    def __len__(self) -> int:
        with self._lock:
            return len(self._traces)

    def trace_ids(self) -> List[int]:
        with self._lock:
            return list(self._traces)

    def get(self, trace_id: int) -> Optional[dict]:
        """The assembled view of one trace (None when unknown)."""
        with self._lock:
            ent = self._traces.get(int(trace_id))
            segs = list(ent.values()) if ent else None
        if segs is None:
            return None
        return assemble(int(trace_id), segs)

    def assembled(self, limit: Optional[int] = None) -> List[dict]:
        """Assembled traces, newest-first."""
        with self._lock:
            items = [(tid, list(ent.values()))
                     for tid, ent in reversed(self._traces.items())]
        if limit is not None:
            items = items[:int(limit)]
        return [assemble(tid, segs) for tid, segs in items]

    def latest(self, replica: Optional[str] = None) -> Optional[Tuple[int, float]]:
        """The newest kept ``(trace_id, duration_ms)`` for a replica
        (None = fleet-wide) — what the ``/metrics`` exemplars point
        at."""
        with self._lock:
            return self._latest.get(replica)


# -- Perfetto export -----------------------------------------------------------


def trace_record_to_chrome_events(rec: dict, pid: int = 1) -> List[dict]:
    """One kept-trace segment -> Chrome trace-event JSON events (the
    per-process half ``tracing.merge_chrome_traces`` joins into the
    fleet view — ``scripts/qt_trace.py --export`` writes each segment
    through this and merges along the existing path)."""
    label = rec.get("replica") or f"trace {rec.get('trace_id')}"
    events: List[dict] = [
        {"ph": "M", "pid": pid, "tid": 0, "name": "process_name",
         "args": {"name": str(label)}}]
    for s in rec.get("spans") or ():
        ev = {"ph": "X", "pid": pid, "tid": 1,
              "name": s.get("name", "?"),
              "cat": str(s.get("name", "?")).split(".", 1)[0],
              "ts": round(float(s.get("t0_ms") or 0.0) * 1e3, 3),
              "dur": round(max(float(s.get("dur_ms") or 0.0), 0.0) * 1e3,
                           3)}
        args = dict(s.get("args") or {})
        args["trace_id"] = rec.get("trace_id")
        ev["args"] = args
        events.append(ev)
    return events
