"""Distributed communication backend — XLA collectives over ICI/DCN.

Replaces the reference's three-mechanism stack (survey §5: CUDA-IPC,
P2P peer loads, raw NCCL wrapper + hand-rolled exchange schedule,
quiver_comm.cu:9-100 + comm.py:5-186) with the single TPU-native
mechanism: a global ``jax.sharding.Mesh`` and collectives inside
``shard_map``. There is no id bootstrap (``getNcclId``/TCPStore) —
``jax.distributed.initialize`` wires up DCN; the function is kept as an
API-compat no-op token.

``HostRankTable`` and ``schedule`` reproduce the reference's rank
bookkeeping and contention-free pairwise scheduling (comm.py:5-75) for
host-driven exchange planning; the on-device path doesn't need them (the
XLA collective scheduler owns link contention).
"""

from __future__ import annotations

from typing import List, Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, PartitionSpec as P
from ._compat import shard_map
from .ops import quant
from .ops.dedup import I32_MAX, unique_within_budget
from .profiling import hot_path


def get_comm_id() -> bytes:
    """API-compat shim for ``quiver.getNcclId`` (comm.py:185-186). TPU
    bootstrap happens in ``jax.distributed.initialize``; nothing to mint."""
    return b"quiver-tpu-comm"


def init_distributed(coordinator_address: Optional[str] = None,
                     num_processes: Optional[int] = None,
                     process_id: Optional[int] = None):
    """Multi-host bootstrap (replaces NcclId + TCPStore rendezvous)."""
    jax.distributed.initialize(coordinator_address, num_processes, process_id)


class HostRankTable:
    """(host, lane) <-> global rank mapping (reference comm.py:5-39)."""

    def __init__(self, hosts: int, rank_per_host: int):
        self.hosts = hosts
        self.rank_per_host = rank_per_host
        self.world_size = hosts * rank_per_host

    def rank(self, host: int, lane: int) -> int:
        return host * self.rank_per_host + lane

    def host_lane(self, rank: int):
        return divmod(rank, self.rank_per_host)

    def ranks_of_host(self, host: int) -> List[int]:
        base = host * self.rank_per_host
        return list(range(base, base + self.rank_per_host))


def schedule(size_matrix: np.ndarray) -> List[List[tuple]]:
    """Greedy contention-free step packing of pairwise transfers
    (capability parity with reference comm.py:42-75): given an ws x ws
    byte matrix, emit steps where no rank appears twice, biggest first."""
    sizes = np.array(size_matrix, dtype=np.int64, copy=True)
    ws = sizes.shape[0]
    np.fill_diagonal(sizes, 0)
    steps: List[List[tuple]] = []
    while sizes.any():
        busy = set()
        step = []
        order = np.argsort(sizes, axis=None)[::-1]
        for flat in order:
            src, dst = divmod(int(flat), ws)
            if sizes[src, dst] == 0 or src in busy or dst in busy:
                continue
            step.append((src, dst))
            busy.add(src)
            busy.add(dst)
            sizes[src, dst] = 0
        steps.append(step)
    return steps


def build_exchange_fn(mesh: Mesh, axis: str, rows_per_host: int, cap: int,
                      dtype=None):
    """One jitted SPMD program implementing the full DistFeature exchange
    (reference comm.py:127-182's two send/recv loops + local gather):

      req_ids [H, H, cap]  req_ids[s, d] = local row ids host s wants of d
      feat    [H*rows_per_host, dim] row-sharded over ``axis`` — a plain
              array or a quantized-tier pytree (``ops.quant``)
      -> resp [H, H, cap, dim]  resp[s, d] = rows host s got from host d

    One ``all_to_all`` ships requests, a local gather reads rows, a second
    ``all_to_all`` ships responses — the reference's allreduced size matrix
    and scheduled pair steps collapse into the collective itself. A
    quantized store ships the NARROW payload + per-row sidecars through
    the response collective and dequantizes after it, so DCN bytes per
    row shrink with the storage width. ``dtype`` is the caller's payload
    dtype (None = the store's own dequantized dtype — never a silent
    fp32 default).
    """

    def body(req, feat):
        # local views: req [1, H, cap], feat [rows_per_host, dim]
        incoming = jax.lax.all_to_all(req, axis, split_axis=1, concat_axis=0)
        ids = jnp.clip(incoming[:, 0, :], 0, rows_per_host - 1)   # [H, cap]
        ship = lambda leaf: jax.lax.all_to_all(
            leaf[ids], axis, split_axis=0, concat_axis=0)
        # quantized payloads cross the collective narrow; dequant AFTER
        resp = quant.dequantize(quant.tree_map_tier(ship, feat), dtype)
        return resp[None]                                         # [1,H,cap,dim]

    mapped = shard_map(
        body, mesh=mesh,
        in_specs=(P(axis), P(axis)),
        out_specs=P(axis),
        check_vma=False)
    return jax.jit(mapped)


def cap_for_expected_load(per_owner: float, slack: float = 1.25) -> int:
    """THE cap-sizing formula, shared by ``default_exchange_cap`` and
    ``PartitionInfo.plan_exchange_cap`` so the headroom term can't
    drift between them: ``slack`` proportional headroom plus ~3-sigma
    binomial headroom on the expected per-owner unique-request load.
    The sqrt term is what small batches need (a 128-unique batch over
    8 owners overflows a bare mean-sized bucket ~half the time, since
    per-owner skew is relative to sqrt(count)); at production counts
    it vanishes into the slack term."""
    return max(1, int(np.ceil(slack * per_owner
                              + 3.0 * np.sqrt(max(per_owner, 0.0)))))


def default_exchange_cap(batch: int, hosts: int, slack: float = 1.25) -> int:
    """Per-owner request-slot budget for the compact exchange when the
    caller has no partition statistics: assume a multi-hop-frontier
    duplicate factor of >= 8 (bench fanouts run 10-50x) and balanced
    ownership, with ``slack`` headroom for per-owner skew. Callers with
    a real partition should prefer
    ``PartitionInfo.plan_exchange_cap`` (degree-mass-aware sizing)."""
    uniq = max(batch // 8, hosts)
    return min(batch, cap_for_expected_load(uniq / hosts, slack))


@hot_path
def dist_lookup_local(ids: jax.Array, g2h: jax.Array, loc: jax.Array,
                      feat, axis: str, h_count: int,
                      rows_per_host: int, dtype=None, rep=None,
                      exchange_cap: Optional[int] = None,
                      collector=None):
    """The per-shard body of the fused DistFeature lookup — callable from
    INSIDE any ``shard_map`` over ``axis`` (e.g. the multi-host fused
    train step composes it with sampling and the model step):

      ids  [B] this shard's global node ids, -1 fill
      g2h/loc [N] replicated owner / local-row maps
      feat [rows_per_host, dim] this shard's rows — a plain array or a
           quantized-tier pytree (``ops.quant.QuantizedTensor``)
      -> [B, dim] feature rows (zeros at -1 fill)

    Bucket ids by owner (one-hot + cumsum), scatter into a static
    request block, one ``all_to_all`` ships requests, a local gather
    reads rows, a second ``all_to_all`` ships responses, and a final
    gather unbuckets them into batch order. A quantized ``feat`` ships
    the narrow rows + per-row sidecars through the response collective
    and dequantizes only the unbucketed result — the exchange moves
    storage-width bytes, not fp32. ``rep`` optionally carries
    (is_rep [N], rep_rank [N], bases [H]) for replicated-node
    resolution against the calling host's replica tail. ``dtype`` is
    the output dtype; None (the default) uses the store's own
    dequantized dtype — a bf16 store must never silently upcast
    through a hardcoded fp32 here.

    ``exchange_cap`` (None = dense) switches the collectives to the
    COMPACT deduplicated layout: the frontier's valid ids dedup once
    into a static table (``ops.dedup.unique_within_budget``, budget
    ``min(cap*H, B)``), the *unique* ids bucket by owner into a
    [H, cap] request block — the same shape ``build_exchange_fn``
    uses — and the wire carries [H, cap] requests + [H, cap, width]
    responses instead of [H, B] / [H, B, width]; the inverse map
    expands the unique rows back to batch order. A multi-hop frontier
    is mostly -1 padding plus repeated hub ids, so ``B/cap``-ish fewer
    bytes cross DCN while each distinct remote row moves exactly once.
    When the unique count overflows the table or any per-owner bucket
    overflows ``cap``, a ``lax.cond`` falls back to the dense path —
    bit-identical output in every case (dequant is elementwise, so
    expand-after-dequant equals dequant-after-expand). The overflow
    flag is ``pmax``-reduced over ``axis`` first: the branch must be
    UNIFORM across shards or the collectives inside it would deadlock.

    ``collector`` (optional ``metrics.Collector``) records the branch
    telemetry the cap planner flies blind on: whether the dense
    fallback fired, the peak per-owner bucket load vs ``cap``, and the
    dedup dup statistics — all from values this function already
    computes OUTSIDE the ``lax.cond`` (the shard-uniform pmax'd flag
    included), so collection adds no host sync and cannot perturb the
    branch decision or the output.
    """
    batch = ids.shape[0]
    valid = ids >= 0
    n_nodes = g2h.shape[0]

    def route(ids_, valid_):
        """Global id -> (owning host, local row); -1 owner at invalid
        slots (so they match no bucket). Clips from above too: the
        compact path's unique table carries int32-max fill."""
        safe = jnp.clip(ids_, 0, n_nodes - 1)
        owner = jnp.where(valid_, g2h[safe], -1)
        local = loc[safe]
        if rep:
            # replicated nodes resolve locally: owner := this host,
            # local := this host's replica-tail base + rank in the set
            is_rep, rep_rank, bases = rep
            me = jax.lax.axis_index(axis).astype(owner.dtype)
            r = is_rep[safe]
            owner = jnp.where(valid_ & r, me, owner)
            local = jnp.where(r, bases[me] + rep_rank[safe], local)
        return owner, local

    def bucket(owner, local, valid_, cap_):
        """Scatter ids into a [H, cap_] per-owner request block.
        Returns (req, my_pos, counts): counts[h] = valid ids owned by
        h — the compact path's overflow test; slots past ``cap_`` are
        positively out-of-bounds and dropped."""
        onehot = owner[None, :] == jnp.arange(
            h_count, dtype=owner.dtype)[:, None]            # [H, n]
        bucket_pos = jnp.cumsum(onehot, axis=1) - 1         # [H, n]
        my_pos = jnp.sum(jnp.where(onehot, bucket_pos, 0), axis=0)
        # invalid (-1 fill) entries must route to a POSITIVELY
        # out-of-bounds row: `.at[...].set(mode="drop")` resolves
        # negative indices NumPy-style BEFORE the bounds check, so
        # owner=-1 would silently overwrite host H-1's bucket slot 0
        owner_idx = jnp.where(valid_, owner, h_count)
        req = jnp.zeros((h_count, cap_), jnp.int32).at[
            owner_idx, my_pos].set(local, mode="drop")
        return req, my_pos, jnp.sum(onehot, axis=1)

    def exchange(req, owner, my_pos):
        """The collective pair: requests out, local gather, responses
        back, unbucket to the caller's slot order ([n, dim])."""
        with jax.named_scope("qt_exchange_requests"):
            incoming = jax.lax.all_to_all(
                req, axis, split_axis=0, concat_axis=0)
            read = jnp.clip(incoming, 0, rows_per_host - 1)

        def ship(leaf):
            with jax.named_scope("qt_exchange_gather"):
                rows = leaf[read]
            with jax.named_scope("qt_exchange_responses"):
                resp = jax.lax.all_to_all(
                    rows, axis, split_axis=0, concat_axis=0)
            return resp[jnp.clip(owner, 0), my_pos]

        # narrow payload + sidecars cross the collective; dequant
        # happens on the unbucketed result, after the exchange
        return quant.dequantize(quant.tree_map_tier(ship, feat))

    with jax.named_scope("qt_exchange_route"):
        owner, local = route(ids, valid)
    if collector is not None:
        from .metrics import EXCH_CALLS
        collector.add(EXCH_CALLS, 1)

    def dense_bucket():
        with jax.named_scope("qt_exchange_bucket"):
            return bucket(owner, local, valid, batch)

    def dense(_=None):
        # the lax.cond fallback body: must NOT touch the collector —
        # entries recorded inside a cond branch would leak its tracers
        req, my_pos, _counts = dense_bucket()
        return exchange(req, owner, my_pos)

    if exchange_cap is None or int(exchange_cap) >= batch:
        req, my_pos, counts = dense_bucket()
        if collector is not None:
            from .metrics import EXCH_BUCKET_MAX
            collector.peak(EXCH_BUCKET_MAX, jnp.max(counts))
        out = exchange(req, owner, my_pos)
    else:
        cap = int(exchange_cap)
        u_budget = min(cap * h_count, batch)
        uniq, inv, n_uniq = unique_within_budget(ids, u_budget,
                                                 valid=valid,
                                                 collector=collector)
        u_valid = uniq != I32_MAX
        with jax.named_scope("qt_exchange_bucket"):
            owner_u, local_u = route(uniq, u_valid)
            req_u, my_pos_u, counts = bucket(owner_u, local_u, u_valid,
                                             cap)
        bad = (n_uniq > u_budget) | (jnp.max(counts) > cap)
        # the branch carries collectives: every shard must take the
        # same one, so one scalar pmax unifies the overflow flag
        bad = jax.lax.pmax(bad.astype(jnp.int32), axis) > 0
        if collector is not None:
            # recorded OUTSIDE the cond, on the already-pmax'd flag —
            # the predicate itself is untouched
            from .metrics import EXCH_BUCKET_MAX, EXCH_CAP, EXCH_FALLBACK
            collector.add(EXCH_FALLBACK, bad)
            collector.peak(EXCH_BUCKET_MAX, jnp.max(counts))
            collector.peak(EXCH_CAP, cap)

        def compact(_):
            rows_u = exchange(req_u, owner_u,
                              jnp.minimum(my_pos_u, cap - 1))
            return jnp.take(rows_u, inv, axis=0)

        out = jax.lax.cond(bad, dense, compact, None)

    if dtype is None:
        dtype = out.dtype
    return jnp.where(valid[:, None], out, 0).astype(dtype)


def build_dist_lookup_fn(mesh: Mesh, axis: str, rows_per_host: int,
                         batch_per_host: int, dtype=None,
                         with_replicate: bool = False,
                         exchange_cap: Optional[int] = None,
                         collect_metrics: bool = False,
                         merge_counters: bool = False):
    """The WHOLE DistFeature lookup as one jitted SPMD program
    (reference feature.py:555-567 dispatch + comm.py:127-182 exchange +
    scatter, fused):

      ids  [H*B] global node ids, -1 fill, sharded over ``axis``
      g2h  [N]   node -> owning host            (replicated)
      loc  [N]   node -> local row on its owner (replicated)
      feat [H*rows_per_host, dim] row-sharded over ``axis`` — a plain
           array or a quantized-tier pytree (the P(axis) spec applies
           leaf-wise as a pytree prefix, so int8 rows and their
           sidecars shard together and the exchange ships narrow)
      -> out [H*B, dim] sharded over ``axis`` (zeros at -1 fill);
         dtype = the store's dequantized dtype unless ``dtype`` is
         given explicitly (no silent fp32 default)

    Per shard: bucket ids by owner (one-hot + cumsum — jittable, no host
    round trip), scatter into a [H, B] request block, one ``all_to_all``
    ships requests, a local gather reads rows, a second ``all_to_all``
    ships responses, and a final gather unbuckets them into batch order.

    With ``with_replicate`` the program takes three extra replicated
    operands (is_rep [N] bool, rep_rank [N], bases [H]) and resolves
    replicated nodes against the calling host's replica tail
    (reference feature.py:510-526's replicate override).

    ``exchange_cap`` (None = dense) switches the exchange to the
    compact deduplicated [H, cap] layout — see ``dist_lookup_local``.

    ``collect_metrics=True`` adds a second output: the per-shard
    ``[H, metrics.NUM_COUNTERS]`` int32 device counter block (fallback
    flag, peak bucket load vs cap, dedup statistics) — pure jnp
    accumulation, no host sync, rows bit-identical either way.

    ``merge_counters=True`` (requires ``collect_metrics``) folds that
    block over ``axis`` ON DEVICE before it leaves the program
    (``metrics.pmerge_counters`` — psum add slots, pmax max slots) and
    returns ONE replicated ``[metrics.NUM_COUNTERS]`` vector instead of
    the per-shard block: on a real multi-host mesh, where each process
    can only address its own shard of a ``P(axis)`` output, every
    host then observes the GLOBAL hit/fallback/dup picture. Two extra
    int32-vector collectives per lookup; rows bit-identical either way.
    """
    h_count = mesh.shape[axis]
    if merge_counters and not collect_metrics:
        raise ValueError("merge_counters=True requires "
                         "collect_metrics=True")

    def body(ids, g2h, loc, feat, *rep):
        col = None
        if collect_metrics:
            from .metrics import Collector
            col = Collector()
        out = dist_lookup_local(ids.reshape(-1), g2h, loc, feat, axis,
                                h_count, rows_per_host, dtype,
                                rep=rep or None,
                                exchange_cap=exchange_cap,
                                collector=col)
        if collect_metrics:
            if merge_counters:
                from .metrics import pmerge_counters
                return out, pmerge_counters(col.counters(), axis)
            return out, col.counters()[None]
        return out

    specs = (P(axis), P(), P(), P(axis))
    if with_replicate:
        specs += (P(), P(), P())
    if collect_metrics:
        # merged counters are replicated (every shard holds the global
        # vector after the psum/pmax), so they leave unsharded
        outs = (P(axis), P()) if merge_counters else (P(axis), P(axis))
    else:
        outs = P(axis)
    mapped = shard_map(
        body, mesh=mesh,
        in_specs=specs,
        out_specs=outs,
        check_vma=False)
    return jax.jit(mapped)


class TpuComm:
    """Cross-host exchange driver with the reference ``NcclComm`` surface
    (rank/world_size, allreduce, exchange; quiver_comm.cu:17-86 +
    comm.py:78-182).

    Modes:
    - SPMD (mesh given): requests/responses ride ``all_to_all`` over the
      mesh's host axis — works identically on a virtual CPU mesh, a TPU
      slice (ICI), or multi-slice (DCN).
    - simulation (``peers`` registry): in-process stand-ins for the other
      hosts' Features, for single-process tests of the dispatch protocol.
    """

    def __init__(self, rank: int, world_size: int,
                 comm_id=None, hosts: Optional[int] = None,
                 rank_per_host: int = 1,
                 mesh: Optional[Mesh] = None, axis: str = "host",
                 peers: Optional[dict] = None):
        self.rank = rank
        self.world_size = world_size
        self.table = HostRankTable(hosts or world_size, rank_per_host)
        self.mesh = mesh
        self.axis = axis
        self.peers = peers or {}
        self._exchange_fns = {}

    # -- reference-parity small ops -----------------------------------------
    def allreduce(self, x):
        if self.world_size == 1:
            return x
        from jax.experimental import multihost_utils
        return multihost_utils.process_allgather(jnp.asarray(x)).sum(axis=0)

    def send(self, tensor, dst: int):
        raise NotImplementedError(
            "point-to-point sends do not exist on TPU; use exchange() — "
            "the all_to_all collective is the native equivalent")

    recv = send

    # -- the real path -------------------------------------------------------
    def exchange(self, host_ids: Sequence[np.ndarray], feature):
        """Fetch rows from every remote host. host_ids[h] = local row ids
        this rank needs from host h. Returns per-host row blocks
        (None for self / empty)."""
        results: List[Optional[jax.Array]] = [None] * self.table.hosts
        for h in range(self.table.hosts):
            if h == self.rank or host_ids[h].size == 0:
                continue
            if h in self.peers:
                results[h] = self.peers[h][jnp.asarray(host_ids[h])]
            else:
                raise ValueError(
                    f"no peer registered for host {h} and no mesh-driven "
                    "path engaged: under a mesh, use DistFeature (its "
                    "lookup runs the fused SPMD exchange) or "
                    "exchange_spmd()/build_dist_lookup_fn directly")
        return results

    def exchange_spmd(self, req_ids: jax.Array, feat: jax.Array,
                      cap: Optional[int] = None) -> jax.Array:
        """Single-controller SPMD exchange over the mesh host axis.
        req_ids [H, H, cap] (-1 fill), feat [H*rows, dim] sharded.
        ``cap`` is the per-owner request-slot budget — the knob the
        compact fused exchange shares (``exchange_cap``); None derives
        it from ``req_ids``'s own trailing dimension, so callers that
        already built a capped block don't repeat themselves."""
        if self.mesh is None:
            raise ValueError("exchange_spmd needs a mesh")
        if cap is None:
            cap = int(req_ids.shape[-1])
        h = self.mesh.shape[self.axis]
        rows = quant.tier_rows(feat) // h
        # the store's ACTUAL payload dtype keys (and parameterizes) the
        # program — a bf16 or quantized store never upcasts to fp32
        key = (rows, cap, quant.tier_key(feat))
        fn = self._exchange_fns.get(key)
        if fn is None:
            fn = build_exchange_fn(self.mesh, self.axis, rows, cap,
                                   quant.tier_dtype(feat))
            self._exchange_fns[key] = fn
        return fn(req_ids, feat)
