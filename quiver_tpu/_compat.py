"""Version-compat shims for the jax API surface this package uses.

``shard_map`` graduated from ``jax.experimental.shard_map`` to the
top-level ``jax`` namespace, and its replication-check kwarg was renamed
``check_rep`` -> ``check_vma`` in the same move. The package targets the
new spelling; this shim keeps older runtimes (>= 0.4.30) importable by
translating the kwarg and resolving the symbol from wherever the
installed jax provides it.
"""

from __future__ import annotations

# NOTE on old-jax GSPMD numerics (documented, deliberately NOT patched
# here): the GSPMD paths assume value-stable partitioning — random draws
# and sort/scan results identical regardless of how XLA shards the
# program. jax 0.4.x falls short twice: jax_threefry_partitionable
# defaults off (sharding-dependent random streams), and the CPU SPMD
# partitioner itself produces sharding-dependent sort/compaction output,
# which no config flag repairs. Flipping the threefry default from an
# import would silently change EVERY seeded jax.random stream in the
# host program — worse than the disease — so instead the gspmd parity
# tests probe the partitioner and skip where it is not value-stable
# (tests/test_gspmd.py), and users on modern jax (partitionable by
# default, fixed partitioner) get stable results with no global
# mutation.

try:  # new-style: top-level export, check_vma kwarg
    from jax import shard_map as _shard_map

    _KWARG = "check_vma"
except ImportError:  # pragma: no cover - depends on installed jax
    from jax.experimental.shard_map import shard_map as _shard_map

    _KWARG = "check_rep"


def shard_map(f=None, /, *, mesh, in_specs, out_specs, check_vma=None,
              **kwargs):
    """``jax.shard_map`` with the modern signature on any supported jax."""
    if check_vma is not None:
        kwargs[_KWARG] = check_vma
    if f is None:
        return lambda g: _shard_map(g, mesh=mesh, in_specs=in_specs,
                                    out_specs=out_specs, **kwargs)
    return _shard_map(f, mesh=mesh, in_specs=in_specs,
                      out_specs=out_specs, **kwargs)


def pallas_tpu_compiler_params(**kwargs):
    """Construct the pallas-TPU compiler-params dataclass across the
    ``TPUCompilerParams`` -> ``CompilerParams`` rename, dropping fields
    (e.g. ``has_side_effects``) the installed version doesn't know."""
    import dataclasses

    from jax.experimental.pallas import tpu as pltpu

    cls = getattr(pltpu, "CompilerParams", None) or \
        getattr(pltpu, "TPUCompilerParams")
    known = {f.name for f in dataclasses.fields(cls)}
    return cls(**{k: v for k, v in kwargs.items() if k in known})


__all__ = ["shard_map", "pallas_tpu_compiler_params"]
