"""Relational GCN over heterogeneous sampled layers.

The R-GCN capability for the MAG240M-class config (BASELINE configs[3]):
per-relation weight matrices, mean aggregation per relation, summed into
the destination type, plus a per-type self transform.

Consumes ``HeteroLayer`` hops from ``quiver_tpu.hetero`` (outermost hop
first). Per-type frontiers are prefix-ordered (pre-hop frontier first),
so the PyG ``x_target = x[:cap]`` pattern works per node type.
"""

from __future__ import annotations

from typing import Dict

import flax.linen as nn
import jax

from .sage import masked_mean_aggregate


class RGCNConv(nn.Module):
    out_dim: int

    @nn.compact
    def __call__(self, x: Dict[str, jax.Array], adjs: Dict[tuple, jax.Array]):
        agg: Dict[str, jax.Array] = {}
        dst_cap: Dict[str, int] = {}
        for (src_t, rel, dst_t), adj in adjs.items():
            mean = masked_mean_aggregate(
                x[src_t], adj.edge_index, adj.size[1])
            h = nn.Dense(self.out_dim, use_bias=False,
                         name=f"rel__{src_t}__{rel}__{dst_t}")(mean)
            agg[dst_t] = agg.get(dst_t, 0) + h
            dst_cap[dst_t] = adj.size[1]
        out = {}
        for dst_t, msg in agg.items():
            x_dst = x[dst_t][:dst_cap[dst_t]]
            out[dst_t] = nn.Dense(self.out_dim,
                                  name=f"self__{dst_t}")(x_dst) + msg
        return out


class RGCN(nn.Module):
    """Multi-hop R-GCN; returns logits for the seed-type targets."""

    hidden_dim: int
    out_dim: int
    num_layers: int
    seed_type: str
    dropout: float = 0.5

    @nn.compact
    def __call__(self, x: Dict[str, jax.Array], hetero_layers,
                 *, train: bool = False):
        for i, layer in enumerate(hetero_layers):
            last = i == self.num_layers - 1
            dim = self.out_dim if last else self.hidden_dim
            x = RGCNConv(dim, name=f"conv{i}")(x, layer.adjs)
            if not last:
                x = {t: nn.Dropout(self.dropout,
                                   deterministic=not train)(nn.relu(v))
                     for t, v in x.items()}
        return x[self.seed_type]
