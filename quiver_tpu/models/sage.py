"""GraphSAGE in flax, over the static-shape masked layer format.

The reference keeps the model in PyG (``SAGEConv``; e.g.
examples/multi_gpu/pyg/ogb-products/dist_sampling_ogb_products_quiver.py)
— the framework's job is feeding it. Here the model is in-tree so the
whole step (sample -> gather -> forward/backward) is one XLA program.

Message passing is mean aggregation via ``segment_sum`` over the layer's
COO; -1-filled (invalid) edges contribute nothing because their mask
zeroes the message and the count.
"""

from __future__ import annotations


import flax.linen as nn
import jax
import jax.numpy as jnp


def masked_mean_aggregate(x_src: jax.Array, edge_index: jax.Array,
                          num_targets: int) -> jax.Array:
    """Mean of neighbor features per target node. edge_index [2, E] with
    row 0 = source local id, row 1 = target local id, -1 fill."""
    src, dst = edge_index[0], edge_index[1]
    valid = (src >= 0) & (dst >= 0)
    s = jnp.where(valid, src, 0)
    d = jnp.where(valid, dst, 0)
    msg = x_src[s] * valid[:, None].astype(x_src.dtype)
    agg = jax.ops.segment_sum(msg, d, num_segments=num_targets)
    cnt = jax.ops.segment_sum(valid.astype(x_src.dtype), d,
                              num_segments=num_targets)
    return agg / jnp.maximum(cnt, 1.0)[:, None]


class SAGEConv(nn.Module):
    """h_t' = W_root h_t + W_nbr mean_{s in N(t)} h_s"""

    out_dim: int
    use_bias: bool = True

    @nn.compact
    def __call__(self, x_src, x_dst, edge_index):
        num_targets = x_dst.shape[0]
        mean_nbr = masked_mean_aggregate(x_src, edge_index, num_targets)
        h = nn.Dense(self.out_dim, use_bias=self.use_bias,
                     name="lin_root")(x_dst)
        h = h + nn.Dense(self.out_dim, use_bias=False,
                         name="lin_nbr")(mean_nbr)
        return h


class GraphSAGE(nn.Module):
    """Layer-wise minibatch GraphSAGE (PyG NeighborSampler pattern:
    ``x_target = x[:size[1]]`` per hop, adjs outermost-first)."""

    hidden_dim: int
    out_dim: int
    num_layers: int
    dropout: float = 0.5

    @nn.compact
    def __call__(self, x, adjs, *, train: bool = False):
        for i, adj in enumerate(adjs):
            num_targets = adj.size[1]
            x_target = x[:num_targets]
            dim = self.out_dim if i == self.num_layers - 1 else self.hidden_dim
            x = SAGEConv(dim, name=f"conv{i}")(x, x_target, adj.edge_index)
            if i != self.num_layers - 1:
                x = nn.relu(x)
                x = nn.Dropout(self.dropout, deterministic=not train)(x)
        return x
