from .sage import SAGEConv, GraphSAGE
from .gat import GATConv, GAT

__all__ = ["SAGEConv", "GraphSAGE", "GATConv", "GAT"]
