from .sage import SAGEConv, GraphSAGE
from .gat import GATConv, GAT
from .rgcn import RGCNConv, RGCN
from .mag import MAG240MGNN

__all__ = ["SAGEConv", "GraphSAGE", "GATConv", "GAT",
           "RGCNConv", "RGCN", "MAG240MGNN"]
