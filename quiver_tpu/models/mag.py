"""MAG240M-style deep GNN: GAT or GraphSAGE trunk + skip connections +
norm + MLP head.

Capability parity with the reference benchmark model
(benchmarks/ogbn-mag240m/train_quiver_multi_node.py:187-245): per-hop
conv, skip Linear for the GAT variant, norm + ReLU/ELU, dropout, then a
2-layer MLP classifier. LayerNorm stands in for BatchNorm1d (stateless
under jit; same normalization role)."""

from __future__ import annotations

import flax.linen as nn

from .gat import GATConv
from .sage import SAGEConv


class MAG240MGNN(nn.Module):
    model: str                      # 'graphsage' | 'gat'
    hidden_dim: int
    out_dim: int
    num_layers: int
    heads: int = 4
    dropout: float = 0.5

    @nn.compact
    def __call__(self, x, adjs, *, train: bool = False):
        assert self.model in ("graphsage", "gat")
        for i, adj in enumerate(adjs):
            x_target = x[:adj.size[1]]
            if self.model == "gat":
                conv = GATConv(self.hidden_dim // self.heads,
                               heads=self.heads, concat=True,
                               name=f"conv{i}")
                h = conv(x, x_target, adj.edge_index)
                h = h + nn.Dense(self.hidden_dim, name=f"skip{i}")(x_target)
                h = nn.elu(nn.LayerNorm(name=f"norm{i}")(h))
            else:
                conv = SAGEConv(self.hidden_dim, name=f"conv{i}")
                h = conv(x, x_target, adj.edge_index)
                h = nn.relu(nn.LayerNorm(name=f"norm{i}")(h))
            x = nn.Dropout(self.dropout, deterministic=not train)(h)
        h = nn.Dense(self.hidden_dim, name="mlp0")(x)
        h = nn.relu(nn.LayerNorm(name="mlp_norm")(h))
        h = nn.Dropout(self.dropout, deterministic=not train)(h)
        return nn.Dense(self.out_dim, name="mlp1")(h)
