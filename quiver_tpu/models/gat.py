"""GAT in flax over the masked layer format (BASELINE.json configs[4]:
"GAT on ogbn-products with attention-weighted neighbor sampling").

Edge softmax is a masked segment-softmax: invalid (-1) edges get -inf
logits, so padding never leaks attention mass.
"""

from __future__ import annotations

import flax.linen as nn
import jax
import jax.numpy as jnp

NEG_INF = -1e30


def segment_softmax(logits: jax.Array, segment_ids: jax.Array,
                    num_segments: int, valid: jax.Array) -> jax.Array:
    """Softmax over edges grouped by target segment, masked."""
    logits = jnp.where(valid, logits, NEG_INF)
    seg_max = jax.ops.segment_max(logits, segment_ids,
                                  num_segments=num_segments)
    seg_max = jnp.where(jnp.isfinite(seg_max), seg_max, 0.0)
    shifted = jnp.where(valid, logits - seg_max[segment_ids], NEG_INF)
    expd = jnp.where(valid, jnp.exp(shifted), 0.0)
    denom = jax.ops.segment_sum(expd, segment_ids, num_segments=num_segments)
    return expd / jnp.maximum(denom[segment_ids], 1e-16)


class GATConv(nn.Module):
    out_dim: int
    heads: int = 1
    concat: bool = True
    negative_slope: float = 0.2

    @nn.compact
    def __call__(self, x_src, x_dst, edge_index):
        h, f = self.heads, self.out_dim
        num_targets = x_dst.shape[0]
        src, dst = edge_index[0], edge_index[1]
        valid = (src >= 0) & (dst >= 0)
        s = jnp.where(valid, src, 0)
        d = jnp.where(valid, dst, 0)

        w_src = nn.Dense(h * f, use_bias=False, name="lin_src")(x_src)
        w_dst = nn.Dense(h * f, use_bias=False, name="lin_dst")(x_dst)
        w_src = w_src.reshape(-1, h, f)
        w_dst = w_dst.reshape(-1, h, f)

        att_src = self.param("att_src", nn.initializers.glorot_uniform(),
                             (h, f))
        att_dst = self.param("att_dst", nn.initializers.glorot_uniform(),
                             (h, f))
        alpha_src = (w_src * att_src).sum(-1)        # [S, h]
        alpha_dst = (w_dst * att_dst).sum(-1)        # [T, h]
        logits = nn.leaky_relu(alpha_src[s] + alpha_dst[d],
                               negative_slope=self.negative_slope)  # [E, h]

        out = []
        msgs = w_src[s]                              # [E, h, f]
        for head in range(h):
            a = segment_softmax(logits[:, head], d, num_targets, valid)
            weighted = msgs[:, head, :] * a[:, None]
            out.append(jax.ops.segment_sum(weighted, d,
                                           num_segments=num_targets))
        stacked = jnp.stack(out, axis=1)             # [T, h, f]
        if self.concat:
            return stacked.reshape(num_targets, h * f)
        return stacked.mean(axis=1)


class GAT(nn.Module):
    hidden_dim: int
    out_dim: int
    num_layers: int
    heads: int = 4
    dropout: float = 0.5

    @nn.compact
    def __call__(self, x, adjs, *, train: bool = False):
        for i, adj in enumerate(adjs):
            x_target = x[:adj.size[1]]
            last = i == self.num_layers - 1
            conv = GATConv(self.out_dim if last else self.hidden_dim,
                           heads=1 if last else self.heads,
                           concat=not last, name=f"conv{i}")
            x = conv(x, x_target, adj.edge_index)
            if not last:
                x = nn.elu(x)
                x = nn.Dropout(self.dropout, deterministic=not train)(x)
        return x
