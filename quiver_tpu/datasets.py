"""Real-dataset adapter: load a user's OGB-style numpy dump into the
framework's native structures.

The environment this framework ships from has no dataset egress, so (like
the repo's examples) tests run on synthetics — but a user with a real
dataset (ogbn-products, Reddit, ...) should not have to hand-roll the
glue the reference's examples get from ``PygNodePropPredDataset``
(reference examples/pyg/reddit_quiver.py:1-60,
examples/multi_gpu/pyg/ogb-products/dist_sampling_ogb_products_quiver.py).
One ``numpy`` export on any machine with the data:

    import numpy as np
    from ogb.nodeproppred import PygNodePropPredDataset
    ds = PygNodePropPredDataset("ogbn-products", root=...)
    data, split = ds[0], ds.get_idx_split()
    np.savez("products.npz",
             edge_index=data.edge_index.numpy(),
             feat=data.x.numpy(),
             labels=data.y.numpy().squeeze(),
             train_idx=split["train"].numpy(),
             valid_idx=split["valid"].numpy(),
             test_idx=split["test"].numpy())

then loads here as ``from_numpy_dir("products.npz")`` (a directory of
per-key ``.npy`` files with the same names works too) and plugs straight
into ``CSRTopo`` + ``Feature`` + the train loops
(``examples/train_products_synthetic.py --data-dir``).
"""

from __future__ import annotations

import os
from typing import NamedTuple, Optional

import numpy as np

from .utils import CSRTopo

#: required keys and their expected rank
_REQUIRED = {"edge_index": 2, "feat": 2, "labels": 1, "train_idx": 1}
_OPTIONAL = {"valid_idx": 1, "test_idx": 1}


class GraphDataset(NamedTuple):
    """A loaded node-classification dataset, framework-native.

    ``csr_topo`` is ready for any sampler; ``feat``/``labels`` are host
    numpy (hand ``feat`` to ``quiver_tpu.Feature`` with whatever cache
    policy fits the machine); ``*_idx`` are the official splits
    (``valid_idx``/``test_idx`` may be None).
    """

    csr_topo: CSRTopo
    feat: np.ndarray
    labels: np.ndarray
    train_idx: np.ndarray
    valid_idx: Optional[np.ndarray]
    test_idx: Optional[np.ndarray]

    @property
    def num_classes(self) -> int:
        # papers100M-style dumps store float labels with NaN on
        # unlabeled nodes; classes count over the labeled ones
        finite = self.labels[np.isfinite(
            self.labels.astype(np.float64, copy=False))]
        if finite.size == 0:
            raise ValueError("labels contain no finite entries")
        return int(finite.max()) + 1


def _load_mapping(path: str) -> dict:
    """Accept either a ``.npz`` bundle or a directory of ``.npy`` files
    named after the keys."""
    if os.path.isfile(path):
        return dict(np.load(path))
    if os.path.isdir(path):
        out = {}
        for key in {**_REQUIRED, **_OPTIONAL}:
            f = os.path.join(path, key + ".npy")
            if os.path.exists(f):
                out[key] = np.load(f)
        return out
    raise FileNotFoundError(
        f"{path!r} is neither an .npz file nor a directory of .npy files")


def from_numpy_dir(path: str, undirected: bool = False) -> GraphDataset:
    """Load an OGB-style numpy dump (see module docstring for the
    one-liner that produces it) into ``GraphDataset``.

    Required keys: ``edge_index`` [2, E] int, ``feat`` [N, dim],
    ``labels`` [N] (an [N, 1] column is squeezed), ``train_idx``.
    Optional: ``valid_idx``, ``test_idx``. ``undirected=True`` adds the
    reverse of every edge (OGB products/Reddit dumps are already
    symmetric; set it for directed dumps when the model expects
    symmetric message passing).
    """
    data = _load_mapping(path)
    missing = [k for k in _REQUIRED if k not in data]
    if missing:
        raise KeyError(
            f"dataset at {path!r} is missing key(s) {missing}; expected "
            f"{sorted(_REQUIRED)} (+ optional {sorted(_OPTIONAL)})")

    labels = np.asarray(data["labels"])
    if labels.ndim == 2 and labels.shape[1] == 1:
        labels = labels[:, 0]
    # some exports mark unlabeled nodes with an integer -1 instead of
    # NaN; -1 passes isfinite and would flow into the loss as a real
    # class. Normalize negative sentinels to the NaN convention (loudly
    # — the dtype widens to float) so num_classes and eval masks see
    # them as unlabeled.
    finite = np.isfinite(labels.astype(np.float64, copy=False))
    if bool((labels[finite] < 0).any()):
        from .debug import log as _log
        neg = int((labels[finite] < 0).sum())
        _log("labels contain %d negative entries; treating them as "
             "unlabeled (NaN convention, papers100M-style)", neg)
        labels = labels.astype(np.float32)
        labels[labels < 0] = np.nan
    feat = np.ascontiguousarray(data["feat"])
    for key, rank in {**_REQUIRED, **_OPTIONAL}.items():
        if key in data and key != "labels" and np.asarray(data[key]).ndim != rank:
            raise ValueError(
                f"{key} must be rank {rank}, got shape "
                f"{np.asarray(data[key]).shape}")
    if labels.ndim != 1:
        raise ValueError(f"labels must be [N] or [N, 1], got {labels.shape}")

    edge_index = np.asarray(data["edge_index"])
    if edge_index.shape[0] != 2:
        raise ValueError(
            f"edge_index must be [2, E], got {edge_index.shape}")
    n = feat.shape[0]
    if labels.shape[0] != n:
        raise ValueError(
            f"feat has {n} rows but labels has {labels.shape[0]}")
    if edge_index.size and int(edge_index.max()) >= n:
        raise ValueError(
            f"edge_index references node {int(edge_index.max())} but "
            f"feat only has {n} rows")
    if edge_index.size and int(edge_index.min()) < 0:
        # a -1 sentinel would silently wrap to node n-1 in the CSR build
        raise ValueError(
            f"edge_index contains negative node id "
            f"{int(edge_index.min())}")
    if undirected:
        edge_index = np.concatenate(
            [edge_index, edge_index[::-1]], axis=1)

    def _idx(key):
        if key not in data:
            return None
        idx = np.asarray(data[key]).astype(np.int64)
        if idx.size and (idx.min() < 0 or idx.max() >= n):
            raise ValueError(f"{key} out of range [0, {n})")
        return idx

    topo = CSRTopo(edge_index=edge_index, node_count=n)
    return GraphDataset(csr_topo=topo, feat=feat, labels=labels,
                        train_idx=_idx("train_idx"),
                        valid_idx=_idx("valid_idx"),
                        test_idx=_idx("test_idx"))
