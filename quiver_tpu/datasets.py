"""Real-dataset adapter: load a user's OGB-style numpy dump into the
framework's native structures.

The environment this framework ships from has no dataset egress, so (like
the repo's examples) tests run on synthetics — but a user with a real
dataset (ogbn-products, Reddit, ...) should not have to hand-roll the
glue the reference's examples get from ``PygNodePropPredDataset``
(reference examples/pyg/reddit_quiver.py:1-60,
examples/multi_gpu/pyg/ogb-products/dist_sampling_ogb_products_quiver.py).
One ``numpy`` export on any machine with the data:

    import numpy as np
    from ogb.nodeproppred import PygNodePropPredDataset
    ds = PygNodePropPredDataset("ogbn-products", root=...)
    data, split = ds[0], ds.get_idx_split()
    np.savez("products.npz",
             edge_index=data.edge_index.numpy(),
             feat=data.x.numpy(),
             labels=data.y.numpy().squeeze(),
             train_idx=split["train"].numpy(),
             valid_idx=split["valid"].numpy(),
             test_idx=split["test"].numpy())

then loads here as ``from_numpy_dir("products.npz")`` (a directory of
per-key ``.npy`` files with the same names works too) and plugs straight
into ``CSRTopo`` + ``Feature`` + the train loops
(``examples/train_products_synthetic.py --data-dir``).
"""

from __future__ import annotations

import json
import math
import os
from typing import NamedTuple, Optional

import numpy as np

from .utils import CSRTopo

#: required keys and their expected rank
_REQUIRED = {"edge_index": 2, "feat": 2, "labels": 1, "train_idx": 1}
_OPTIONAL = {"valid_idx": 1, "test_idx": 1}


class GraphDataset(NamedTuple):
    """A loaded node-classification dataset, framework-native.

    ``csr_topo`` is ready for any sampler; ``feat``/``labels`` are host
    numpy (hand ``feat`` to ``quiver_tpu.Feature`` with whatever cache
    policy fits the machine); ``*_idx`` are the official splits
    (``valid_idx``/``test_idx`` may be None).
    """

    csr_topo: CSRTopo
    feat: np.ndarray
    labels: np.ndarray
    train_idx: np.ndarray
    valid_idx: Optional[np.ndarray]
    test_idx: Optional[np.ndarray]

    @property
    def num_classes(self) -> int:
        # papers100M-style dumps store float labels with NaN on
        # unlabeled nodes; classes count over the labeled ones
        finite = self.labels[np.isfinite(
            self.labels.astype(np.float64, copy=False))]
        if finite.size == 0:
            raise ValueError("labels contain no finite entries")
        return int(finite.max()) + 1


def _load_mapping(path: str) -> dict:
    """Accept either a ``.npz`` bundle or a directory of ``.npy`` files
    named after the keys."""
    if os.path.isfile(path):
        return dict(np.load(path))
    if os.path.isdir(path):
        out = {}
        for key in {**_REQUIRED, **_OPTIONAL}:
            f = os.path.join(path, key + ".npy")
            if os.path.exists(f):
                out[key] = np.load(f)
        return out
    raise FileNotFoundError(
        f"{path!r} is neither an .npz file nor a directory of .npy files")


def from_numpy_dir(path: str, undirected: bool = False) -> GraphDataset:
    """Load an OGB-style numpy dump (see module docstring for the
    one-liner that produces it) into ``GraphDataset``.

    Required keys: ``edge_index`` [2, E] int, ``feat`` [N, dim],
    ``labels`` [N] (an [N, 1] column is squeezed), ``train_idx``.
    Optional: ``valid_idx``, ``test_idx``. ``undirected=True`` adds the
    reverse of every edge (OGB products/Reddit dumps are already
    symmetric; set it for directed dumps when the model expects
    symmetric message passing).
    """
    data = _load_mapping(path)
    missing = [k for k in _REQUIRED if k not in data]
    if missing:
        raise KeyError(
            f"dataset at {path!r} is missing key(s) {missing}; expected "
            f"{sorted(_REQUIRED)} (+ optional {sorted(_OPTIONAL)})")

    labels = np.asarray(data["labels"])
    if labels.ndim == 2 and labels.shape[1] == 1:
        labels = labels[:, 0]
    # some exports mark unlabeled nodes with an integer -1 instead of
    # NaN; -1 passes isfinite and would flow into the loss as a real
    # class. Normalize negative sentinels to the NaN convention (loudly
    # — the dtype widens to float) so num_classes and eval masks see
    # them as unlabeled.
    finite = np.isfinite(labels.astype(np.float64, copy=False))
    if bool((labels[finite] < 0).any()):
        from .debug import log as _log
        neg = int((labels[finite] < 0).sum())
        _log("labels contain %d negative entries; treating them as "
             "unlabeled (NaN convention, papers100M-style)", neg)
        labels = labels.astype(np.float32)
        labels[labels < 0] = np.nan
    feat = np.ascontiguousarray(data["feat"])
    for key, rank in {**_REQUIRED, **_OPTIONAL}.items():
        if key in data and key != "labels" and np.asarray(data[key]).ndim != rank:
            raise ValueError(
                f"{key} must be rank {rank}, got shape "
                f"{np.asarray(data[key]).shape}")
    if labels.ndim != 1:
        raise ValueError(f"labels must be [N] or [N, 1], got {labels.shape}")

    edge_index = np.asarray(data["edge_index"])
    if edge_index.shape[0] != 2:
        raise ValueError(
            f"edge_index must be [2, E], got {edge_index.shape}")
    n = feat.shape[0]
    if labels.shape[0] != n:
        raise ValueError(
            f"feat has {n} rows but labels has {labels.shape[0]}")
    if edge_index.size and int(edge_index.max()) >= n:
        raise ValueError(
            f"edge_index references node {int(edge_index.max())} but "
            f"feat only has {n} rows")
    if edge_index.size and int(edge_index.min()) < 0:
        # a -1 sentinel would silently wrap to node n-1 in the CSR build
        raise ValueError(
            f"edge_index contains negative node id "
            f"{int(edge_index.min())}")
    if undirected:
        edge_index = np.concatenate(
            [edge_index, edge_index[::-1]], axis=1)

    def _idx(key):
        if key not in data:
            return None
        idx = np.asarray(data[key]).astype(np.int64)
        if idx.size and (idx.min() < 0 or idx.max() >= n):
            raise ValueError(f"{key} out of range [0, {n})")
        return idx

    topo = CSRTopo(edge_index=edge_index, node_count=n)
    return GraphDataset(csr_topo=topo, feat=feat, labels=labels,
                        train_idx=_idx("train_idx"),
                        valid_idx=_idx("valid_idx"),
                        test_idx=_idx("test_idx"))


# -- synthetic bigger-than-RAM (papers100M-shaped) generator ----------------
# The cold-tier machinery needs a graph whose feature rows do NOT fit
# in RAM to be benchable on one host; no dataset egress exists here, so
# generate one: power-law degrees sorted DESCENDING (identity storage
# order IS the hot order — no permutation artifact needed), skewed
# neighbor popularity (frontiers hit hot rows super-uniformly, like
# real degree-proportional access), and the feature rows streamed in
# chunks straight into a quantized disk-tier artifact
# (partition.save_disk_tier) — the full-width feature matrix never
# materializes, so nodes=111M (papers100M scale, a ~15 GB int8
# artifact at dim 128) generates in bounded memory.

_COLD_META = "meta.json"

#: internal generation block (rows/edges): content is produced per
#: FIXED block keyed by (seed, block start), so ``chunk_rows`` — the
#: streaming/IO unit — cannot change the dataset (pinned in
#: tests/test_prefetch.py)
_GEN_BLOCK = 8192


def _gen_block(seed: int, lo: int, hi: int, total: int, shape_tail, fn):
    """Values [lo, hi) assembled from fixed ``_GEN_BLOCK``-sized
    deterministic blocks of the [0, total) stream: ``fn(rng, count)``
    draws one block's worth. Block boundaries depend only on ``total``,
    never on the requested [lo, hi) — chunk-size invariant."""
    out = None
    b = (lo // _GEN_BLOCK) * _GEN_BLOCK
    while b < hi:
        be = min(b + _GEN_BLOCK, total)
        block = fn(np.random.default_rng([seed, b]), be - b)
        s, e = max(lo, b), min(hi, be)
        if out is None:
            out = np.empty((hi - lo,) + tuple(shape_tail), block.dtype)
        out[s - lo:e - lo] = block[s - b:e - b]
        b = be
    return out


def generate_synthetic_cold_dataset(out_dir: str, nodes: int = 1_000_000,
                                    dim: int = 128, avg_deg: int = 15,
                                    hot_frac: float = 0.05,
                                    dtype_policy: str = "int8",
                                    skew: float = 2.0, classes: int = 64,
                                    seed: int = 0,
                                    chunk_rows: int = 1 << 17,
                                    overwrite: bool = False) -> dict:
    """Write a synthetic papers100M-SHAPED dataset whose feature rows
    live on disk::

        out_dir/indptr.npy, indices.npy     (CSR; degrees descending)
        out_dir/labels.npy
        out_dir/hot_rows.npy                (first ceil(hot_frac * N)
                                             rows, DECODED — the HBM
                                             tier seed)
        out_dir/disk/...                    (save_disk_tier artifact
                                             spanning ALL N rows;
                                             disk_map = identity)
        out_dir/meta.json

    Neighbor ids draw as ``floor(N * u**skew)`` — density concentrated
    on the low (high-degree, HBM-cached) ids, so sampled frontiers show
    a realistic hot-tier hit rate instead of the uniform ``hot_frac``.
    ``hot_rows.npy`` holds the *decoded* quantized rows, so HBM and
    disk lookups agree exactly (quantization error lives in the
    artifact once, not in the tier boundary).
    ``load_synthetic_cold_dataset`` rebuilds ``(CSRTopo, Feature)``.
    """
    from .ops import quant
    from .partition import save_disk_tier

    if not 0.0 < hot_frac <= 1.0:
        raise ValueError(f"hot_frac must be in (0, 1], got {hot_frac}")
    os.makedirs(out_dir, exist_ok=True)
    meta_path = os.path.join(out_dir, _COLD_META)
    if os.path.exists(meta_path) and not overwrite:
        raise FileExistsError(
            f"{meta_path} exists; pass overwrite=True to replace it")
    rng = np.random.default_rng(seed)

    # graph: lognormal degrees, sorted descending (storage order = hot
    # order), neighbor popularity ∝ the same ordering via u**skew
    deg = np.clip(np.exp(rng.normal(np.log(max(avg_deg, 1)), 1.0,
                                    nodes)), 0, 50_000).astype(np.int64)
    deg[::-1].sort()                     # descending, in place
    indptr = np.zeros(nodes + 1, np.int64)
    np.cumsum(deg, out=indptr[1:])
    e = int(indptr[-1])
    idx_path = os.path.join(out_dir, "indices.npy")
    indices = np.lib.format.open_memmap(idx_path, mode="w+",
                                        dtype=np.int32, shape=(e,))

    def draw_edges(r, k):
        return np.minimum((nodes * r.random(k) ** skew),
                          nodes - 1).astype(np.int32)

    edge_chunk = max(chunk_rows * max(avg_deg, 1), 1 << 20)
    for lo in range(0, e, edge_chunk):
        hi = min(lo + edge_chunk, e)
        indices[lo:hi] = _gen_block(seed + 1, lo, hi, e, (), draw_edges)
    indices.flush()
    np.save(os.path.join(out_dir, "indptr.npy"), indptr)
    np.save(os.path.join(out_dir, "labels.npy"),
            rng.integers(0, classes, nodes).astype(np.int32))

    # features: streamed through quantization into the disk artifact
    def read_chunk(lo, hi):
        return _gen_block(
            seed + 2, lo, hi, nodes, (dim,),
            lambda r, k: r.standard_normal((k, dim)).astype(np.float32))

    disk_dir = os.path.join(out_dir, "disk")
    tier_meta = save_disk_tier((read_chunk, nodes, dim),
                               np.arange(nodes, dtype=np.int64),
                               disk_dir, dtype_policy=dtype_policy,
                               overwrite=overwrite,
                               chunk_rows=chunk_rows)

    # hot tier seed: the DECODED first rows of the artifact (chunked)
    hot_rows = max(int(np.ceil(nodes * hot_frac)), 1)
    mm = np.load(os.path.join(disk_dir, "disk_rows.npy"), mmap_mode="r")
    if tier_meta["dtype_policy"] == "int8":
        tier = quant.QuantizedTensor(
            mm, np.load(os.path.join(disk_dir, "disk_scale.npy")),
            np.load(os.path.join(disk_dir, "disk_zero.npy")))
    else:
        tier = mm
    hot = np.lib.format.open_memmap(
        os.path.join(out_dir, "hot_rows.npy"), mode="w+",
        dtype=np.dtype(tier_meta["logical_dtype"]), shape=(hot_rows, dim))
    for lo in range(0, hot_rows, chunk_rows):
        hi = min(lo + chunk_rows, hot_rows)
        hot[lo:hi] = quant.take_np(tier, np.arange(lo, hi))
    hot.flush()
    del mm, hot

    meta = {"kind": "synthetic_cold", "nodes": nodes, "dim": dim,
            "edges": e, "avg_deg": avg_deg, "hot_rows": hot_rows,
            "hot_frac": hot_frac, "skew": skew, "classes": classes,
            "seed": seed, "dtype_policy": tier_meta["dtype_policy"]}
    with open(meta_path, "w") as fh:
        json.dump(meta, fh)
    return meta


def load_synthetic_cold_dataset(out_dir: str,
                                prefetch_rows: Optional[int] = None,
                                depth: int = 2,
                                decode_staged: bool = True,
                                **prefetch_kwargs):
    """Rebuild a generated dataset as framework-native structures:
    ``(csr_topo, feature, meta)``. The :class:`~quiver_tpu.feature.
    Feature` holds ``hot_rows.npy`` in the HBM tier and the full row
    space on the mmap disk tier; ``prefetch_rows`` attaches the
    frontier-keyed cold prefetcher (``enable_cold_prefetch``) with that
    ring capacity, and ``prefetch_kwargs`` forward to it (``workers``,
    ``io_qd``, ... — the parallel-IO staging knobs). The caller owns
    ``feature.close()``."""
    from .feature import DeviceConfig, Feature
    from .partition import load_disk_tier

    with open(os.path.join(out_dir, _COLD_META)) as fh:
        meta = json.load(fh)
    indptr = np.load(os.path.join(out_dir, "indptr.npy"))
    indices = np.load(os.path.join(out_dir, "indices.npy"),
                      mmap_mode="r")
    topo = CSRTopo(indptr=indptr, indices=indices)
    hot = np.load(os.path.join(out_dir, "hot_rows.npy"))
    store = Feature()
    store.from_mmap(None, DeviceConfig([hot], None))
    kwargs, _ = load_disk_tier(os.path.join(out_dir, "disk"))
    store.set_mmap_file(**kwargs)
    if prefetch_rows:
        store.enable_cold_prefetch(prefetch_rows, depth=depth,
                                   decode_staged=decode_staged,
                                   **prefetch_kwargs)
    return topo, store, meta


def generate_drifting_trace(length: int, nodes: int,
                            skew: float = 2.0,
                            rotate_every: int = 1 << 14,
                            stride: Optional[int] = None,
                            hot_frac: float = 0.05,
                            seed: int = 0, lo: int = 0,
                            hi: Optional[int] = None) -> np.ndarray:
    """A seeded node-id trace whose power-law HOT SET rotates on a
    schedule — the adversarial input adaptive caching (the qt-act
    actuator's hot-set rotation) must win on and static placement
    must lose on.

    Each position draws a popularity RANK ``floor(nodes * u**skew)``
    (density concentrated on low ranks — the
    :func:`generate_synthetic_cold_dataset` neighbor idiom), then the
    rank maps to a node id shifted by the position's drift phase::

        phase = index // rotate_every
        id    = (rank + phase * stride) % nodes

    so inside one phase the trace is a stationary power-law over a
    contiguous hot set, and every ``rotate_every`` positions the
    WHOLE popularity ordering shifts by ``stride`` ids (default: the
    hot-set width, ``ceil(nodes * hot_frac)`` — each drift lands the
    new hot set entirely outside the old one). The first phase
    (indices ``[0, rotate_every)``) is the STATIONARY PREFIX the A/B
    protocol scores "no worse than static" on.

    Chunk-invariant like the cold generator: ranks come from fixed
    ``_GEN_BLOCK``-sized blocks keyed ``(seed, block_start)`` and the
    phase depends only on the ABSOLUTE index, so any ``[lo, hi)``
    slicing assembles the identical trace (pinned in
    tests/test_actuator.py). Returns int64 ids in ``[0, nodes)``."""
    if length < 0:
        raise ValueError(f"length must be >= 0, got {length}")
    if nodes < 1:
        raise ValueError(f"nodes must be >= 1, got {nodes}")
    if rotate_every < 1:
        raise ValueError(
            f"rotate_every must be >= 1, got {rotate_every}")
    if stride is None:
        stride = max(1, int(math.ceil(nodes * float(hot_frac))))
    hi = length if hi is None else hi
    if not 0 <= lo <= hi <= length:
        raise ValueError(f"need 0 <= lo <= hi <= length, got "
                         f"[{lo}, {hi}) of {length}")
    if hi == lo:
        return np.empty((0,), np.int64)
    ranks = _gen_block(
        seed, lo, hi, length, (),
        lambda r, k: np.minimum((nodes * r.random(k) ** skew),
                                nodes - 1).astype(np.int64))
    phase = np.arange(lo, hi, dtype=np.int64) // int(rotate_every)
    return (ranks + phase * int(stride)) % int(nodes)
