from .mesh import make_mesh, replicated, row_sharded
from .train import (
    TrainState,
    build_train_step,
    build_e2e_train_step,
    cross_entropy_logits,
)

__all__ = [
    "make_mesh",
    "replicated",
    "row_sharded",
    "TrainState",
    "build_train_step",
    "build_e2e_train_step",
    "cross_entropy_logits",
]
