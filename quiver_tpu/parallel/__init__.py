from .mesh import make_mesh, replicated, row_sharded
from .train import (
    TrainState,
    build_train_step,
    build_e2e_train_step,
    build_split_train_step,
    cross_entropy_logits,
    dedup_feature_gather,
    masked_feature_gather,
)
from .gspmd import build_gspmd_train_step, shard_state, state_sharding
from .dist import build_dist_train_step

__all__ = [
    "make_mesh",
    "replicated",
    "row_sharded",
    "TrainState",
    "build_train_step",
    "build_e2e_train_step",
    "build_split_train_step",
    "build_gspmd_train_step",
    "build_dist_train_step",
    "dedup_feature_gather",
    "masked_feature_gather",
    "shard_state",
    "state_sharding",
    "cross_entropy_logits",
]
