"""Multi-host fused training: sample + distributed feature exchange +
forward/backward + update as ONE XLA program.

The TPU answer to the reference's multi-node training benchmark
(benchmarks/ogbn-papers100M/train_quiver_multi_node.py:270-411: per-rank
DDP processes, DistFeature lookups through the hand-scheduled NCCL
exchange, TCPStore bootstrap). Here every host's shard, inside a single
``shard_map`` over the ``host`` axis:

  1. samples its own seed shard's k-hop frontier (topology replicated),
  2. fetches the frontier's feature rows from whichever hosts own them —
     the fused dispatch + ``all_to_all`` exchange + scatter of
     ``comm.dist_lookup_local`` (features stay partitioned, nothing is
     ever all-gathered),
  3. runs forward/backward and ``pmean``s gradients.

One jit, zero host round trips, no bootstrap beyond
``jax.distributed.initialize``; the same program runs on the virtual
CPU mesh, a TPU slice (ICI), or multi-slice (DCN). The loss definition
is literally the shared ``_fused_loss`` with the feature gather swapped
for the partitioned exchange, so dist/DP loss parity holds exactly
(tests/test_dist_train.py).
"""

from __future__ import annotations

from typing import Callable, Sequence

import jax
from .._compat import shard_map
from jax.sharding import Mesh, PartitionSpec as P

from ..comm import default_exchange_cap, dist_lookup_local
from ..pyg.sage_sampler import layer_shapes
from .train import (TrainState, _check_donatable, _check_rows,
                    _fused_loss, _metered_loss_fn, _pmean_update,
                    cross_entropy_logits, _COLLECT_DOC, _DONATED_DOC)


def build_dist_train_step(model, tx, sizes: Sequence[int],
                          per_host_batch: int, mesh: Mesh,
                          rows_per_host: int,
                          axis: str = "host",
                          loss_fn: Callable = cross_entropy_logits,
                          method: str = "exact",
                          indices_stride: int | None = None,
                          with_replicate: bool = False,
                          hub_frac: float | None = None,
                          donate: bool = True,
                          exchange_cap=None,
                          collect_metrics: bool = False,
                          merge_counters: bool = False):
    """fn(state, spmd_feat, g2h, g2l, indptr, indices, seeds, labels,
    key[, indices_rows][, is_rep, rep_rank, bases]) -> (state, loss).

    ``spmd_feat`` [H*rows_per_host, dim] is the partition-sharded store
    (``DistFeature.from_partition``'s layout — pass ``dist._spmd_feat``;
    a ``dtype_policy`` store passes its QuantizedTensor pytree whole:
    the P(axis) spec shards its leaves together and the exchange ships
    the narrow payload, dequantizing after the collective);
    ``g2h``/``g2l`` the replicated owner / local-row maps
    (``PartitionInfo.global2host/global2local``); ``seeds``/``labels``
    [H*per_host_batch] sharded over ``axis``; topology replicated.

    ``method="rotation"|"window"`` requires the shuffled
    ``indices_rows`` view (refresh per epoch; ``indices_stride=128``
    for the overlapping layout). ``with_replicate=True`` adds the three
    replicated-node operands (``DistFeature._rep_args``) so replicated
    nodes resolve against the calling host's replica tail instead of
    being mis-routed to their owner with a tail-local index.

    ``exchange_cap`` (``True | int | None``) switches the feature
    exchange to the COMPACT deduplicated collective
    (``comm.dist_lookup_local``): the frontier's valid ids dedup once,
    bucket by owner into a static [H, cap] request block, and the wire
    carries [H, cap] / [H, cap, width] instead of the dense
    [H, B] / [H, B, width] — B being the full multi-hop frontier cap,
    mostly -1 padding plus repeated hubs, so this is the step that
    makes the multi-host path bandwidth-optimal. ``True`` sizes ``cap``
    from the frontier cap and host count
    (``comm.default_exchange_cap``); an int pins it — prefer
    ``PartitionInfo.plan_exchange_cap(...).cap``, which sizes from the
    partition's degree mass. Overflowing batches (unique count or any
    per-owner bucket) fall back to the dense path via a shard-uniform
    ``lax.cond`` — loss-identical in every case.
    """
    sizes = list(sizes)
    h_count = mesh.shape[axis]
    if merge_counters and not collect_metrics:
        raise ValueError("merge_counters=True requires "
                         "collect_metrics=True")
    if exchange_cap is True:
        frontier = layer_shapes(per_host_batch, sizes)[-1].n_id_cap
        exchange_cap = default_exchange_cap(frontier, h_count)
    elif exchange_cap is not None:
        exchange_cap = int(exchange_cap)

    def make_per_shard(has_rows):
        # shard_map arity is fixed at build time; ``has_rows`` says
        # whether extra[0] is the rows view (mandatory for
        # rotation/window, optional wide-path input for exact)
        def per_shard(state: TrainState, feat, g2h, g2l, indptr, indices,
                      seeds, labels, key, *extra):
            rows = extra[0] if has_rows else None
            rep = extra[1:] if (has_rows and with_replicate) else \
                (extra if with_replicate else None)
            key = jax.random.fold_in(key, jax.lax.axis_index(axis))

            def gather(feat_, n_id, _forder, collector=None):
                # dtype=None: the lookup resolves the store's own
                # dequantized dtype — a bf16 or quantized spmd_feat
                # must not upcast through an fp32 default, and a
                # QuantizedTensor has no .dtype to pass anyway
                return dist_lookup_local(n_id, g2h, g2l, feat_, axis,
                                         h_count, rows_per_host,
                                         rep=rep or None,
                                         exchange_cap=exchange_cap,
                                         collector=collector)

            loss_of, unpack = _metered_loss_fn(
                collect_metrics,
                lambda p, col: _fused_loss(model, loss_fn, sizes,
                                           per_host_batch, p, feat, None,
                                           indptr, indices, seeds, labels,
                                           key, method, rows,
                                           indices_stride, gather=gather,
                                           hub_frac=hub_frac,
                                           collector=col))
            loss, counters, grads = unpack(loss_of(state.params))
            new_state, loss = _pmean_update(state, tx, grads, loss, axis)
            if collect_metrics:
                if merge_counters:
                    # device-side cross-host fold: every shard leaves
                    # holding the GLOBAL [N] vector (psum/pmax slot
                    # semantics), so any host's local read sees the
                    # whole mesh's picture
                    from ..metrics import pmerge_counters
                    return new_state, loss, pmerge_counters(counters,
                                                            axis)
                # per-shard counters, [1, N] here -> [H, N] outside
                return new_state, loss, counters[None]
            return new_state, loss

        return per_shard

    def make_jitted(has_rows):
        specs = [P(), P(axis), P(), P(), P(), P(), P(axis), P(axis), P()]
        if has_rows:
            specs.append(P())            # indices_rows, replicated
        if with_replicate:
            specs += [P(), P(), P()]     # is_rep, rep_rank, bases
        if collect_metrics:
            outs = (P(), P(), P() if merge_counters else P(axis))
        else:
            outs = (P(), P())
        return jax.jit(shard_map(
            make_per_shard(has_rows), mesh=mesh,
            in_specs=tuple(specs),
            out_specs=outs,
            check_vma=False), donate_argnums=(0,) if donate else ())

    jitted_by_rows = {True: make_jitted(True), False: make_jitted(False)}
    checked = set()

    def step(state, feat, g2h, g2l, indptr, indices, seeds, labels, key,
             indices_rows=None, rep_args=()):
        _check_rows(method, indices_rows, "dist")
        jitted = jitted_by_rows[indices_rows is not None]
        extra = (indices_rows,) if indices_rows is not None else ()
        if with_replicate:
            if len(rep_args) != 3:
                raise TypeError(
                    "with_replicate dist step requires rep_args = "
                    "(is_rep, rep_rank, bases) — pass "
                    "DistFeature._rep_args")
            extra += tuple(rep_args)
        elif rep_args:
            raise TypeError("rep_args given but with_replicate=False")
        if donate:
            _check_donatable("build_dist_train_step", jitted, checked,
                             state, feat, g2h, g2l, indptr, indices,
                             seeds, labels, key, *extra)
        return jitted(state, feat, g2h, g2l, indptr, indices, seeds,
                      labels, key, *extra)

    step.jitted_fns = tuple(jitted_by_rows.values())
    return step


if build_dist_train_step.__doc__:        # None under python -OO
    build_dist_train_step.__doc__ += _DONATED_DOC + _COLLECT_DOC
