"""GSPMD 2-D mesh training: data x model (tensor) parallelism.

Beyond reference parity (the reference's only model story is vanilla
DDP, survey §2.3): the fused train step runs under ``jax.jit`` over a
``(data, model)`` mesh with

- the batch (seeds/labels) sharded over ``data``,
- every 2-D dense kernel of the GNN column-sharded over ``model`` (its
  bias and the following activation column-sharded to match),
- graph topology and features replicated,

and XLA/GSPMD inserts the collectives (the per-layer ``all_gather`` of
the column-sharded activations feeding the next layer's row span, the
cross-``data`` gradient reduction). No shard_map, no hand-written
collectives: annotate shardings, let the partitioner work.

TP is profitable when hidden_dim is large (wide GNNs, e.g.
MAG240M-class 1024-wide configs); at hidden=256 it mostly demonstrates
capability. Numerics match the single-chip step up to reduction order
(tested in tests/test_gspmd.py). Shard-friendly dims: hidden/out dims
should be divisible by the ``model`` axis size.
"""

from __future__ import annotations

from typing import Callable, Sequence

import jax
import optax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from .train import (TrainState, _check_rows, _fused_loss,
                    cross_entropy_logits)


def _leaf_spec(leaf, model_axis: str) -> P:
    """Column-shard 2-D kernels over ``model_axis``; shard 1-D biases
    the same way so each lands with its kernel's output columns;
    replicate scalars/everything else."""
    ndim = getattr(leaf, "ndim", 0)
    if ndim == 2:
        return P(None, model_axis)
    if ndim == 1:
        return P(model_axis)
    return P()


def state_sharding(state: TrainState, mesh: Mesh,
                   model_axis: str = "model"):
    """TP placement for a TrainState: params AND optimizer moments get
    the same layout (adam's mu/nu mirror the param tree), step scalar
    replicated."""
    return jax.tree.map(
        lambda leaf: NamedSharding(mesh, _leaf_spec(leaf, model_axis)),
        state)


def shard_state(state: TrainState, mesh: Mesh,
                model_axis: str = "model") -> TrainState:
    """Place an (unsharded) TrainState onto the mesh with TP layout."""
    return jax.device_put(state, state_sharding(state, mesh, model_axis))


def build_gspmd_train_step(model, tx, sizes: Sequence[int], mesh: Mesh,
                           data_axis: str = "data",
                           model_axis: str = "model",
                           loss_fn: Callable = cross_entropy_logits,
                           method: str = "exact",
                           indices_stride: int | None = None):
    """fn(state, feat, forder, indptr, indices, seeds, labels, key[,
    indices_rows]) -> (state, loss), with ``state`` placed by
    ``shard_state`` and seeds/labels of global batch length (any
    multiple of the ``data`` axis size) sharded over ``data_axis``;
    topology/features (and, for ``method="rotation"|"window"``, the
    per-epoch ``indices_rows`` view) replicated. One jitted program;
    XLA partitions the sampler over the batch shards and the matmuls
    over the model shards."""
    sizes = list(sizes)
    cache = {}

    def step(state: TrainState, feat, forder, indptr, indices, seeds,
             labels, key, *rows):
        loss, grads = jax.value_and_grad(
            lambda p: _fused_loss(model, loss_fn, sizes, seeds.shape[0],
                                  p, feat, forder, indptr, indices, seeds,
                                  labels, key, method,
                                  rows[0] if rows else None,
                                  indices_stride)
        )(state.params)
        updates, opt_state = tx.update(grads, state.opt_state, state.params)
        params = optax.apply_updates(state.params, updates)
        return TrainState(params, opt_state, state.step + 1), loss

    repl = NamedSharding(mesh, P())
    data = NamedSharding(mesh, P(data_axis))

    def sharded_step(state, feat, forder, indptr, indices, seeds, labels,
                     key, indices_rows=None):
        _check_rows(method, indices_rows, "gspmd")
        has_rows = indices_rows is not None   # windowed always; exact may
        fn = cache.get(has_rows)
        if fn is None:
            st_sh = state_sharding(state, mesh, model_axis)
            shardings = [st_sh, repl, repl, repl, repl, data, data, repl]
            if has_rows:
                shardings.append(repl)
            fn = jax.jit(
                step,
                in_shardings=tuple(shardings),
                out_shardings=(st_sh, repl))
            cache[has_rows] = fn
        extra = (indices_rows,) if has_rows else ()
        return fn(state, feat, forder, indptr, indices, seeds, labels,
                  key, *extra)

    return sharded_step
