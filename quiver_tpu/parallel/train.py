"""End-to-end training steps: sample -> gather -> forward/backward -> update
as one XLA program, data-parallel over a mesh.

This replaces the reference's DDP story (survey §2.3: vanilla torch DDP
around Quiver components, per-rank python processes + CUDA-IPC handles,
NCCL allreduce). TPU-native: ONE process per host, `shard_map` over the
``data`` mesh axis; every chip samples its own seed shard, gathers
features, and gradients are `pmean`ed over ICI — no IPC, no NCCL
bootstrap, no per-GPU processes.

Graph topology, the feature array, and the optional hot-order permutation
are explicit arguments of the returned step functions (not closures), so
the same compiled program serves any same-shape graph and nothing large is
baked into the executable as a constant.
"""

from __future__ import annotations

from typing import Any, Callable, NamedTuple, Sequence

import jax
import jax.numpy as jnp
import optax
from .._compat import shard_map
from jax.sharding import Mesh, PartitionSpec as P

from ..ops.sample_multihop import sample_multihop
from ..pyg.sage_sampler import Adj, layer_shapes


class TrainState(NamedTuple):
    params: Any
    opt_state: Any
    step: jax.Array


def cross_entropy_logits(logits: jax.Array, labels: jax.Array) -> jax.Array:
    return optax.softmax_cross_entropy_with_integer_labels(
        logits, labels).mean()


def layers_to_adjs(layers, batch_size: int, sizes: Sequence[int]):
    """LayerSamples (sampling order) -> Adj list (outermost hop first)."""
    shapes = layer_shapes(batch_size, sizes)
    adjs = []
    for layer, shape in zip(layers, shapes):
        adjs.append(Adj(edge_index=jnp.stack([layer.col, layer.row]),
                        e_id=layer.e_id,
                        size=(shape.n_id_cap, shape.num_seeds),
                        mask=layer.col >= 0))
    return adjs[::-1]


def masked_feature_gather(feat: jax.Array, n_id: jax.Array,
                          feature_order=None) -> jax.Array:
    """Feature rows for a -1-padded frontier, through the optional
    hot-order indirection (reference feature.py:296-301); padded rows
    come back zeroed so aggregation stays exact."""
    ids = n_id
    if feature_order is not None:
        ids = feature_order[jnp.clip(n_id, 0)]
    safe = jnp.clip(ids, 0, feat.shape[0] - 1)
    x = jnp.take(feat, safe, axis=0)
    return x * (n_id >= 0).astype(x.dtype)[:, None]


def _fused_loss(model, loss_fn, sizes, batch_size, params, feat, forder,
                indptr, indices, seeds, labels, key, method="exact",
                indices_rows=None, indices_stride=None, gather=None,
                hub_frac=None):
    """``gather(feat, n_id, forder)`` defaults to the local
    ``masked_feature_gather``; the multi-host fused step substitutes the
    partitioned all_to_all lookup. Everything else (sampling keys, the
    dropout fold constant, the logits slice) is THE shared definition —
    dist/DP loss parity depends on there being exactly one copy.

    Batch contract: ``seeds`` must be distinct valid ids with -1 padding
    at the TAIL only. That was always required here — ``labels`` are
    indexed by batch position while interior holes would shift seeds to
    rank-based output rows, silently misaligning the loss — so hop 0
    also takes the cheaper dense-seed compaction path."""
    n_id, layers = sample_multihop(indptr, indices, seeds, sizes, key,
                                   method=method, indices_rows=indices_rows,
                                   indices_stride=indices_stride,
                                   seeds_dense=True, hub_frac=hub_frac)
    x = (gather or masked_feature_gather)(feat, n_id, forder)
    adjs = layers_to_adjs(layers, batch_size, sizes)
    logits = model.apply(params, x, adjs, train=True,
                         rngs={"dropout": jax.random.fold_in(key, 1000)})
    return loss_fn(logits[:batch_size], labels)


def _check_rows(method: str, indices_rows, kind: str) -> bool:
    """Shared indices_rows contract for the step builders: rotation and
    window REQUIRE the per-epoch shuffled view (as_index_rows /
    as_index_rows_overlapping; refresh via permute_csr). exact
    OPTIONALLY takes a layout view of the UN-shuffled indices — that
    switches the scattered draw to the wide-fetch exact path
    (``sample_layer_exact_wide``; same i.i.d. statistics, fewer
    scattered loads). Returns whether the method is windowed."""
    windowed = method in ("rotation", "window")
    if windowed and indices_rows is None:
        raise TypeError(
            f"{method} {kind} step requires indices_rows (the shuffled "
            "as_index_rows/as_index_rows_overlapping view; refresh per "
            "epoch via permute_csr)")
    return windowed


def _pmean_update(state, tx, grads, loss, axis):
    """Cross-shard gradient/loss reduction + optimizer update (shared by
    the shard_map builders)."""
    grads = jax.lax.pmean(grads, axis)
    loss = jax.lax.pmean(loss, axis)
    updates, opt_state = tx.update(grads, state.opt_state, state.params)
    params = optax.apply_updates(state.params, updates)
    return TrainState(params, opt_state, state.step + 1), loss


def build_train_step(model, tx, sizes: Sequence[int], batch_size: int,
                     loss_fn: Callable = cross_entropy_logits,
                     method: str = "exact",
                     indices_stride: int | None = None,
                     hub_frac: float | None = None):
    """Single-chip fused step:
    fn(state, feat, forder, indptr, indices, seeds, labels, key[,
    indices_rows]). With ``method="rotation"`` pass the shuffled
    ``as_index_rows`` view as ``indices_rows`` (refresh per epoch with
    ``reshuffle_csr`` — exact sort or cheap butterfly) — or, with
    ``indices_stride=128``, the
    ``as_index_rows_overlapping`` view (one row gather per seed, 2x
    index memory). With ``method="exact"`` + an un-shuffled layout view
    as ``indices_rows``, pass ``hub_frac`` (the cached
    ``CSRTopo.exact_bucket_meta().frac``) so the wide-exact hub budget
    is sized from the graph's degree-bucket split."""
    sizes = list(sizes)

    @jax.jit
    def step(state: TrainState, feat, forder, indptr, indices, seeds,
             labels, key, indices_rows=None):
        loss, grads = jax.value_and_grad(
            lambda p: _fused_loss(model, loss_fn, sizes, batch_size, p, feat,
                                  forder, indptr, indices, seeds, labels, key,
                                  method, indices_rows, indices_stride,
                                  hub_frac=hub_frac)
        )(state.params)
        updates, opt_state = tx.update(grads, state.opt_state, state.params)
        params = optax.apply_updates(state.params, updates)
        return TrainState(params, opt_state, state.step + 1), loss

    return step


def build_e2e_train_step(model, tx, sizes: Sequence[int],
                         per_device_batch: int, mesh: Mesh,
                         axis: str = "data",
                         loss_fn: Callable = cross_entropy_logits,
                         method: str = "exact",
                         indices_stride: int | None = None,
                         hub_frac: float | None = None):
    """Data-parallel fused step over ``mesh[axis]``:
    fn(state, feat, forder, indptr, indices, seeds, labels, key[,
    indices_rows]) with seeds/labels [n_dev * per_device_batch] sharded
    over ``axis``; state/feat/topology (and the shuffled rows view when
    ``method="rotation"``) replicated; grads pmean over ``axis``.
    ``indices_stride=128`` switches ``indices_rows`` to the
    ``as_index_rows_overlapping`` layout (one row gather per seed).
    ``hub_frac`` (cached ``CSRTopo.exact_bucket_meta().frac``) sizes the
    wide-exact hub budget when exact mode gets an ``indices_rows``."""
    sizes = list(sizes)

    def per_shard(state: TrainState, feat, forder, indptr, indices, seeds,
                  labels, key, indices_rows=None):
        key = jax.random.fold_in(key, jax.lax.axis_index(axis))
        loss, grads = jax.value_and_grad(
            lambda p: _fused_loss(model, loss_fn, sizes, per_device_batch, p,
                                  feat, forder, indptr, indices, seeds,
                                  labels, key, method, indices_rows,
                                  indices_stride, hub_frac=hub_frac)
        )(state.params)
        return _pmean_update(state, tx, grads, loss, axis)

    specs = [P(), P(), P(), P(), P(), P(axis), P(axis), P()]
    # shard_map arity is fixed at build time, but exact may or may not
    # bring the (optional) wide-path rows view — build both arities; jit
    # compiles lazily so the unused one costs nothing
    with_rows = shard_map(
        per_shard, mesh=mesh,
        in_specs=tuple(specs + [P()]),   # indices_rows, replicated
        out_specs=(P(), P()),
        check_vma=False)
    without_rows = shard_map(
        per_shard, mesh=mesh,
        in_specs=tuple(specs),
        out_specs=(P(), P()),
        check_vma=False)
    jitted_rows = jax.jit(with_rows)
    jitted = jax.jit(without_rows)

    # validate the optional arg up front so a mismatch is a clear
    # TypeError, not an opaque shard_map/jit arity failure
    def step(state, feat, forder, indptr, indices, seeds, labels, key,
             indices_rows=None):
        _check_rows(method, indices_rows, "e2e")
        if indices_rows is not None:
            return jitted_rows(state, feat, forder, indptr, indices, seeds,
                               labels, key, indices_rows)
        return jitted(state, feat, forder, indptr, indices, seeds, labels,
                      key)

    return step


def build_split_train_step(model, tx, sizes: Sequence[int], batch_size: int,
                           loss_fn: Callable = cross_entropy_logits,
                           method: str = "exact",
                           indices_stride: int | None = None,
                           hub_frac: float | None = None):
    """Two-phase step for tiered feature stores (the reference's own
    architecture: sampling and feature collection run as separate stages
    around the model, examples/pyg/reddit_quiver.py:116-122):

      sample_fn(indptr, indices, seeds, key[, indices_rows]) -> (n_id, adjs)
      step_fn(state, x, adjs, labels, key) -> (state, loss)

    Use when features live partly on host/disk: sample on device, fetch
    ``x = feature[n_id]`` through the tiered store, then run the fused
    forward/backward/update.
    """
    sizes = list(sizes)

    @jax.jit
    def sample_fn(indptr, indices, seeds, key, indices_rows=None):
        # same batch contract as _fused_loss: distinct valid ids,
        # -1 padding at the tail only (labels are position-indexed)
        n_id, layers = sample_multihop(
            indptr, indices, seeds, sizes, key, method=method,
            indices_rows=indices_rows,
            indices_stride=indices_stride if indices_rows is not None
            else None, seeds_dense=True, hub_frac=hub_frac)
        return n_id, layers_to_adjs(layers, batch_size, sizes)

    @jax.jit
    def step_fn(state: TrainState, x, adjs, labels, key):
        def loss_of(p):
            logits = model.apply(p, x, adjs, train=True,
                                 rngs={"dropout": key})
            return loss_fn(logits[:batch_size], labels)

        loss, grads = jax.value_and_grad(loss_of)(state.params)
        updates, opt_state = tx.update(grads, state.opt_state, state.params)
        params = optax.apply_updates(state.params, updates)
        return TrainState(params, opt_state, state.step + 1), loss

    return sample_fn, step_fn


def init_state(model, tx, example_x, example_adjs, key) -> TrainState:
    params = model.init(key, example_x, example_adjs)
    return TrainState(params, tx.init(params), jnp.zeros((), jnp.int32))
