"""End-to-end training steps: sample -> gather -> forward/backward -> update
as one XLA program, data-parallel over a mesh.

This replaces the reference's DDP story (survey §2.3: vanilla torch DDP
around Quiver components, per-rank python processes + CUDA-IPC handles,
NCCL allreduce). TPU-native: ONE process per host, `shard_map` over the
``data`` mesh axis; every chip samples its own seed shard, gathers
features, and gradients are `pmean`ed over ICI — no IPC, no NCCL
bootstrap, no per-GPU processes.

Graph topology, the feature array, and the optional hot-order permutation
are explicit arguments of the returned step functions (not closures), so
the same compiled program serves any same-shape graph and nothing large is
baked into the executable as a constant.
"""

from __future__ import annotations

from typing import Any, Callable, NamedTuple, Sequence

import jax
import jax.numpy as jnp
import optax
from .._compat import shard_map
from jax.sharding import Mesh, PartitionSpec as P

from ..ops.sample_multihop import sample_multihop
from ..profiling import hot_path
from ..pyg.sage_sampler import Adj, layer_shapes


class TrainState(NamedTuple):
    params: Any
    opt_state: Any
    step: jax.Array


def cross_entropy_logits(logits: jax.Array, labels: jax.Array) -> jax.Array:
    return optax.softmax_cross_entropy_with_integer_labels(
        logits, labels).mean()


def layers_to_adjs(layers, batch_size: int, sizes: Sequence[int]):
    """LayerSamples (sampling order) -> Adj list (outermost hop first)."""
    shapes = layer_shapes(batch_size, sizes)
    adjs = []
    for layer, shape in zip(layers, shapes):
        adjs.append(Adj(edge_index=jnp.stack([layer.col, layer.row]),
                        e_id=layer.e_id,
                        size=(shape.n_id_cap, shape.num_seeds),
                        mask=layer.col >= 0))
    return adjs[::-1]


@hot_path
def masked_feature_gather(feat, n_id: jax.Array,
                          feature_order=None,
                          collector=None) -> jax.Array:
    """Feature rows for a -1-padded frontier, through the optional
    hot-order indirection (reference feature.py:296-301); padded rows
    come back zeroed so aggregation stays exact. ``feat`` may be a
    plain array or a quantized store (``ops.quant`` — e.g.
    ``quant.quantize(feat, "int8")``): dequantization fuses into the
    gather, so the step reads narrow rows + sidecars and the model
    consumes float activations unchanged. ``collector`` is accepted for
    gather-protocol uniformity (single-tier: nothing tiered to count)."""
    from ..ops import quant
    ids = n_id
    if feature_order is not None:
        ids = feature_order[jnp.clip(n_id, 0)]
    safe = jnp.clip(ids, 0, quant.tier_rows(feat) - 1)
    x = quant.gather_rows(feat, safe)
    return x * (n_id >= 0).astype(x.dtype)[:, None]


@hot_path
def dedup_feature_gather(feat, n_id: jax.Array,
                         feature_order=None,
                         budget: int | None = None,
                         collector=None) -> jax.Array:
    """``masked_feature_gather`` reading each distinct valid id ONCE:
    the frontier's -1 padding (the bulk of a static multi-hop cap) and
    any repeated ids collapse into a static-``budget`` unique table,
    the feature read is one [budget, dim] gather, and positions expand
    from it. Falls back to the plain full gather via ``lax.cond`` when
    the unique count overflows — identical output in every case.
    Default budget: ``max(len(n_id)//4, 256)``."""
    from ..ops.dedup import unique_within_budget
    from ..ops.quant import default_cold_budget
    n = n_id.shape[0]
    if budget is None:
        budget = default_cold_budget(n)
    if budget >= n:
        return masked_feature_gather(feat, n_id, feature_order)
    valid = n_id >= 0
    uniq, inv, n_uniq = unique_within_budget(n_id, budget, valid=valid,
                                             collector=collector)

    def narrow(_):
        # uniq's int32-max fill clips to the LAST feature row — those
        # slots hold real (unused) data, NOT zeros: inv never points a
        # valid position at them, and invalid positions carry in-range-
        # garbage inv that the re-mask below zeroes
        rows_u = masked_feature_gather(feat, uniq, feature_order)
        x = jnp.take(rows_u, inv, axis=0)
        return x * valid.astype(x.dtype)[:, None]

    return jax.lax.cond(n_uniq > budget,
                        lambda _: masked_feature_gather(feat, n_id,
                                                        feature_order),
                        narrow, None)


def _fused_multihop_x(feat, forder, indptr, indices, seeds, sizes, key,
                      row_cap=2048, rng=None, interpret=None,
                      hot_rows=None, collector=None):
    """The fused frontier walk (``ops.pallas.fused.fused_multihop``):
    interior hops run the sampling-only fused kernel (in-kernel indptr
    resolution), the leaf hop samples AND gathers in one kernel, and
    the gather-free compaction chains them — frontier ids live only in
    VMEM/SMEM at every hop, so the step's modeled
    ``gather_index_bytes`` is zero across the whole ladder. The layer
    COOs and the ``[cap, dim]`` frontier block come back bit-identical
    to ``masked_feature_gather(feat, n_id, forder)`` over the same
    picks (valid slots).

    The sampling PRNG is the KERNEL's stream (hop ``i`` seeded from
    ``fold_in(key, i)``), not ``jax.random`` — losses are
    bit-comparable with the split Pallas oracle
    (``ops.pallas.fused.fused_multihop_reference``), not with the
    ``sample_multihop`` path. A 1-hop ``sizes`` reduces exactly to the
    qt-fuse single-hop behavior. ``hot_rows`` zeroes rows whose
    (``forder``-translated) storage row falls outside the hot tier;
    callers with a cold tier overlay exactly those slots afterwards
    (the serve step's tiered fixup)."""
    from ..ops.pallas.fused import fused_multihop, pad_indices
    n_id, layers, x = fused_multihop(
        indptr, pad_indices(indices, row_cap), seeds, feat, list(sizes),
        key, row_cap=row_cap, rng=rng, interpret=interpret,
        feature_order=forder, hot_rows=hot_rows)
    if collector is not None:
        from ..metrics import FRONTIER_CAP, FRONTIER_VALID
        collector.add(FRONTIER_VALID, jnp.sum(n_id >= 0))
        collector.add(FRONTIER_CAP, int(n_id.shape[0]))
    return x, layers


def _fused_knobs(enabled, row_cap, rng, interpret, sizes, method,
                 dedup_gather=None, indices_stride=None, hub_frac=None):
    """Validate + pack the ``fused_hot_hop`` builder knobs (shared by
    the train and serve builders). The fused walk covers any
    exact-method fanout ladder (qt-fuse-deep) and does its own
    in-kernel gather, so the knob composes with nothing that reshapes
    sampling or the gather."""
    if not enabled:
        return None
    if not sizes:
        raise ValueError("fused_hot_hop needs at least one hop in sizes")
    if method != "exact":
        raise ValueError(
            f"fused_hot_hop requires method='exact', got {method!r}")
    if dedup_gather is not None:
        raise ValueError(
            "fused_hot_hop gathers in-kernel (one DMA per frontier "
            "slot); dedup_gather does not compose with it")
    if indices_stride is not None or hub_frac is not None:
        raise ValueError(
            "fused_hot_hop takes neither indices_stride nor hub_frac "
            "(no wide-exact/rotation layout views in the fused kernel)")
    return {"row_cap": int(row_cap), "rng": rng, "interpret": interpret}


@hot_path
def _fused_loss(model, loss_fn, sizes, batch_size, params, feat, forder,
                indptr, indices, seeds, labels, key, method="exact",
                indices_rows=None, indices_stride=None, gather=None,
                hub_frac=None, collector=None, fused=None):
    """``gather(feat, n_id, forder, collector=None)`` defaults to the
    local ``masked_feature_gather``; the multi-host fused step
    substitutes the partitioned all_to_all lookup. Everything else
    (sampling keys, the dropout fold constant, the logits slice) is THE
    shared definition — dist/DP loss parity depends on there being
    exactly one copy. ``collector`` (a ``metrics.Collector``) opts into
    device-counter telemetry: sampling and the gather record counts
    they already compute; the loss itself is untouched (bit-identical
    with collection on or off).

    Batch contract: ``seeds`` must be distinct valid ids with -1 padding
    at the TAIL only. That was always required here — ``labels`` are
    indexed by batch position while interior holes would shift seeds to
    rank-based output rows, silently misaligning the loss — so hop 0
    also takes the cheaper dense-seed compaction path.

    ``fused`` (the packed ``fused_hot_hop`` builder knobs, see
    ``_fused_knobs``) swaps the sample->gather pair for the fused
    Pallas walk (``_fused_multihop_x``) — frontier ids stay on chip at
    EVERY hop; everything from the frontier block on is unchanged."""
    if fused is not None:
        if indices_rows is not None:
            raise TypeError(
                "fused_hot_hop does not take indices_rows (the fused "
                "walk does its own in-kernel CSR reads every hop)")
        x, layers = _fused_multihop_x(feat, forder, indptr, indices,
                                      seeds, sizes, key,
                                      collector=collector, **fused)
    else:
        n_id, layers = sample_multihop(
            indptr, indices, seeds, sizes, key, method=method,
            indices_rows=indices_rows, indices_stride=indices_stride,
            seeds_dense=True, hub_frac=hub_frac, collector=collector)
        x = (gather or masked_feature_gather)(feat, n_id, forder,
                                              collector=collector)
    adjs = layers_to_adjs(layers, batch_size, sizes)
    logits = model.apply(params, x, adjs, train=True,
                         rngs={"dropout": jax.random.fold_in(key, 1000)})
    return loss_fn(logits[:batch_size], labels)


def _check_rows(method: str, indices_rows, kind: str) -> bool:
    """Shared indices_rows contract for the step builders: rotation and
    window REQUIRE the per-epoch shuffled view (as_index_rows /
    as_index_rows_overlapping; refresh via permute_csr). exact
    OPTIONALLY takes a layout view of the UN-shuffled indices — that
    switches the scattered draw to the wide-fetch exact path
    (``sample_layer_exact_wide``; same i.i.d. statistics, fewer
    scattered loads). Returns whether the method is windowed."""
    windowed = method in ("rotation", "window")
    if windowed and indices_rows is None:
        raise TypeError(
            f"{method} {kind} step requires indices_rows (the shuffled "
            "as_index_rows/as_index_rows_overlapping view; refresh per "
            "epoch via permute_csr)")
    return windowed


def _pmean_update(state, tx, grads, loss, axis):
    """Cross-shard gradient/loss reduction + optimizer update (shared by
    the shard_map builders)."""
    grads = jax.lax.pmean(grads, axis)
    loss = jax.lax.pmean(loss, axis)
    updates, opt_state = tx.update(grads, state.opt_state, state.params)
    params = optax.apply_updates(state.params, updates)
    return TrainState(params, opt_state, state.step + 1), loss


def _check_donatable(kind, fn, checked, state, *args, **kwargs):
    """Pre-flight guard for donated ``TrainState`` args: XLA quietly
    falls back to a COPY when a donated buffer can't be reused because
    the returned state's shape/dtype/structure drifted (e.g. an optax
    chain that changes a moment's dtype) — the donation "works" but
    every step still reallocates. Trace the step abstractly on the
    FIRST call per jitted fn and fail loudly on any drift. Single-shot
    by design: once the in/out specs match, every later state IS a
    prior output (same specs by induction), so the steady-state cost
    is one O(1) set lookup, not a per-step pytree walk."""
    if id(fn) in checked:
        return
    out_state = jax.eval_shape(fn, state, *args, **kwargs)[0]
    flat_in, tree_in = jax.tree_util.tree_flatten_with_path(state)
    flat_out, tree_out = jax.tree_util.tree_flatten_with_path(out_state)
    if tree_in != tree_out:
        raise ValueError(
            f"{kind}: donated TrainState changes pytree structure "
            f"across the step ({tree_in} -> {tree_out}); donation would "
            "silently copy every buffer. Fix the model/optimizer to "
            "return the same structure, or pass donate=False.")
    bad = [
        (jax.tree_util.keystr(p_in),
         (tuple(jnp.shape(a)), str(jnp.result_type(a))),
         (tuple(b.shape), str(b.dtype)))
        for (p_in, a), (_, b) in zip(flat_in, flat_out)
        if tuple(jnp.shape(a)) != tuple(b.shape)
        or jnp.result_type(a) != b.dtype]
    if bad:
        detail = "; ".join(f"{p}: {i} -> {o}" for p, i, o in bad[:4])
        raise ValueError(
            f"{kind}: donated TrainState leaves change shape/dtype "
            f"across the step ({detail}) — XLA cannot reuse the donated "
            "buffers and would silently copy them every step. Make the "
            "step shape/dtype-stable, or pass donate=False.")
    checked.add(id(fn))


_DONATED_DOC = """

    ``donate=True`` (default) donates the ``state`` argument's buffers
    to the step: the update writes in place instead of reallocating the
    full model+optimizer state every step. The INPUT state is dead
    after the call — use the returned state, and pass ``donate=False``
    when a caller genuinely needs to reuse one state across several
    step calls (A/B parity comparisons). A shape/dtype guard traces the
    step abstractly on first use and raises a clear error if the state
    drifts across the step (which would turn donation into a silent
    per-step copy)."""


def _dedup_gather_fn(dedup_gather):
    """``dedup_gather`` knob -> the gather callable ``_fused_loss``
    takes (None keeps the plain masked gather)."""
    if dedup_gather is None:
        return None
    budget = None if dedup_gather is True else int(dedup_gather)
    return lambda feat, n_id, forder, collector=None: dedup_feature_gather(
        feat, n_id, forder, budget, collector=collector)


def _metered_loss_fn(collect: bool, loss_with_collector):
    """Shared value_and_grad plumbing for the ``collect_metrics`` knob:
    ``loss_with_collector(params, collector_or_None)`` is the loss;
    with collection on, a fresh ``metrics.Collector`` is created INSIDE
    the traced function (a collector outliving a trace would leak stale
    tracers into the next one) and its counter vector rides out as
    ``has_aux`` — differentiation sees the identical loss either way.
    Returns ``(loss_of, unpack)`` with
    ``unpack(loss_of(p)) == (loss, counters_or_None, grads)``."""
    if collect:
        from ..metrics import Collector

        def loss_of(p):
            col = Collector()
            return loss_with_collector(p, col), col.counters()

        vg = jax.value_and_grad(loss_of, has_aux=True)
        return vg, lambda out: (out[0][0], out[0][1], out[1])
    vg = jax.value_and_grad(lambda p: loss_with_collector(p, None))
    return vg, lambda out: (out[0], None, out[1])


_COLLECT_DOC = """

    ``collect_metrics=True`` adds ONE auxiliary output to the step — a
    ``metrics.NUM_COUNTERS`` int32 device counter vector (per-shard
    ``[shards, N]`` from the shard_map builders) carrying the observed
    frontier fill, dedup/dup statistics and exchange branch behavior.
    Counters accumulate with pure jnp ops on values the hot path
    already computes: zero host syncs per step, ``lax.cond``
    predicates untouched, losses bit-identical to the metrics-off step,
    donation intact. Feed the vectors to ``metrics.StepStats`` or a
    ``telemetry.TelemetryHub``. The returned step exposes
    ``.jitted_fns`` (the underlying jitted callables) for
    ``StepStats.watch_compiles``. Shard_map builders additionally take
    ``merge_counters=True``: the per-shard block is folded over the
    mesh axis ON DEVICE (``metrics.pmerge_counters`` — psum add slots,
    pmax max slots) and the step returns one replicated global ``[N]``
    vector — on a real multi-host mesh each process can only address
    its own shard of the per-shard output, so this is how every host
    observes the global picture. Losses stay bit-identical with the
    merge on or off."""


def build_train_step(model, tx, sizes: Sequence[int], batch_size: int,
                     loss_fn: Callable = cross_entropy_logits,
                     method: str = "exact",
                     indices_stride: int | None = None,
                     hub_frac: float | None = None,
                     donate: bool = True,
                     dedup_gather=None,
                     collect_metrics: bool = False,
                     fused_hot_hop: bool = False,
                     fused_row_cap: int = 2048,
                     fused_rng: str | None = None,
                     fused_interpret: bool | None = None):
    """Single-chip fused step:
    fn(state, feat, forder, indptr, indices, seeds, labels, key[,
    indices_rows]). With ``method="rotation"`` pass the shuffled
    ``as_index_rows`` view as ``indices_rows`` (refresh per epoch with
    ``reshuffle_csr`` — exact sort or cheap butterfly) — or, with
    ``indices_stride=128``, the
    ``as_index_rows_overlapping`` view (one row gather per seed, 2x
    index memory). With ``method="exact"`` + an un-shuffled layout view
    as ``indices_rows``, pass ``hub_frac`` (the cached
    ``CSRTopo.exact_bucket_meta().frac``) so the wide-exact hub budget
    is sized from the graph's degree-bucket split. ``dedup_gather``
    (True or an int unique budget) swaps the frontier feature gather
    for ``dedup_feature_gather`` — one read per distinct node instead
    of per frontier slot. ``feat`` may be a quantized store
    (``ops.quant.quantize(feat, "int8"|"bf16")``): dequant fuses into
    the gather and the model consumes float activations unchanged.

    ``fused_hot_hop=True`` (any ``sizes`` ladder, ``method="exact"``
    only) swaps the sample->gather pair for the fused Pallas walk
    (``ops.pallas.fused.fused_multihop``): interior hops run the
    sampling-only fused kernel, the leaf hop fuses reservoir sampling
    with the per-pick feature-row DMA (int8 dequant applied
    in-register), and frontier ids never materialize in HBM at ANY hop
    — the step's modeled ``gather_index_bytes`` is zero across the
    whole ladder. ``fused_row_cap`` bounds the in-VMEM CSR window per
    seed (degrees beyond it are truncated — the sample kernel's
    contract); ``fused_rng``/``fused_interpret`` default to the
    backend-appropriate choices ("tpu" PRNG on TPU, portable "hash" +
    interpret mode elsewhere). The fused step's sampling stream is the
    kernel PRNG (hop ``i`` seeded from ``fold_in(key, i)``), so losses
    are not bit-comparable with the split step — only with the split
    Pallas oracle (``ops.pallas.fused.fused_multihop_reference``)."""
    sizes = list(sizes)
    gather = _dedup_gather_fn(dedup_gather)
    fused = _fused_knobs(fused_hot_hop, fused_row_cap, fused_rng,
                         fused_interpret, sizes, method,
                         dedup_gather=dedup_gather,
                         indices_stride=indices_stride,
                         hub_frac=hub_frac)

    def step(state: TrainState, feat, forder, indptr, indices, seeds,
             labels, key, indices_rows=None):
        loss_of, unpack = _metered_loss_fn(
            collect_metrics,
            lambda p, col: _fused_loss(model, loss_fn, sizes, batch_size,
                                       p, feat, forder, indptr, indices,
                                       seeds, labels, key, method,
                                       indices_rows, indices_stride,
                                       gather=gather, hub_frac=hub_frac,
                                       collector=col, fused=fused))
        loss, counters, grads = unpack(loss_of(state.params))
        updates, opt_state = tx.update(grads, state.opt_state, state.params)
        params = optax.apply_updates(state.params, updates)
        new_state = TrainState(params, opt_state, state.step + 1)
        if collect_metrics:
            return new_state, loss, counters
        return new_state, loss

    jitted = jax.jit(step, donate_argnums=(0,) if donate else ())
    jitted.jitted_fns = (jitted,)
    if not donate:
        return jitted
    checked = set()

    def guarded(state, *args, **kwargs):
        _check_donatable("build_train_step", jitted, checked, state,
                         *args, **kwargs)
        return jitted(state, *args, **kwargs)

    guarded.jitted_fns = (jitted,)
    return guarded


def build_e2e_train_step(model, tx, sizes: Sequence[int],
                         per_device_batch: int, mesh: Mesh,
                         axis: str = "data",
                         loss_fn: Callable = cross_entropy_logits,
                         method: str = "exact",
                         indices_stride: int | None = None,
                         hub_frac: float | None = None,
                         donate: bool = True,
                         dedup_gather=None,
                         collect_metrics: bool = False,
                         merge_counters: bool = False,
                         fused_hot_hop: bool = False,
                         fused_row_cap: int = 2048,
                         fused_rng: str | None = None,
                         fused_interpret: bool | None = None):
    """Data-parallel fused step over ``mesh[axis]``:
    fn(state, feat, forder, indptr, indices, seeds, labels, key[,
    indices_rows]) with seeds/labels [n_dev * per_device_batch] sharded
    over ``axis``; state/feat/topology (and the shuffled rows view when
    ``method="rotation"``) replicated; grads pmean over ``axis``.
    ``indices_stride=128`` switches ``indices_rows`` to the
    ``as_index_rows_overlapping`` layout (one row gather per seed).
    ``hub_frac`` (cached ``CSRTopo.exact_bucket_meta().frac``) sizes the
    wide-exact hub budget when exact mode gets an ``indices_rows``.
    ``dedup_gather`` (True or an int unique budget) swaps each shard's
    frontier feature gather for ``dedup_feature_gather``. ``feat`` may
    be a quantized store (``ops.quant``) — the P() spec broadcasts
    over its leaves as a pytree prefix.

    ``fused_hot_hop=True`` swaps each shard's sample->gather pair for
    the fused Pallas walk (``ops.pallas.fused.fused_multihop``) with
    the same contract as ``build_train_step``: exact method, any
    ``sizes`` ladder, zero modeled ``gather_index_bytes`` per shard;
    the per-shard key fold keeps shards on distinct kernel streams."""
    sizes = list(sizes)
    gather = _dedup_gather_fn(dedup_gather)
    fused = _fused_knobs(fused_hot_hop, fused_row_cap, fused_rng,
                         fused_interpret, sizes, method,
                         dedup_gather=dedup_gather,
                         indices_stride=indices_stride,
                         hub_frac=hub_frac)
    if merge_counters and not collect_metrics:
        raise ValueError("merge_counters=True requires "
                         "collect_metrics=True")

    def per_shard(state: TrainState, feat, forder, indptr, indices, seeds,
                  labels, key, indices_rows=None):
        key = jax.random.fold_in(key, jax.lax.axis_index(axis))
        loss_of, unpack = _metered_loss_fn(
            collect_metrics,
            lambda p, col: _fused_loss(model, loss_fn, sizes,
                                       per_device_batch, p, feat, forder,
                                       indptr, indices, seeds, labels, key,
                                       method, indices_rows, indices_stride,
                                       gather=gather, hub_frac=hub_frac,
                                       collector=col, fused=fused))
        loss, counters, grads = unpack(loss_of(state.params))
        new_state, loss = _pmean_update(state, tx, grads, loss, axis)
        if collect_metrics:
            if merge_counters:
                # device-side cross-shard fold (psum/pmax slot
                # semantics): the step emits ONE global [N] vector
                from ..metrics import pmerge_counters
                return new_state, loss, pmerge_counters(counters, axis)
            # per-shard counters, [1, N] here -> [n_dev, N] outside
            return new_state, loss, counters[None]
        return new_state, loss

    specs = [P(), P(), P(), P(), P(), P(axis), P(axis), P()]
    if collect_metrics:
        outs = (P(), P(), P() if merge_counters else P(axis))
    else:
        outs = (P(), P())
    # shard_map arity is fixed at build time, but exact may or may not
    # bring the (optional) wide-path rows view — build both arities; jit
    # compiles lazily so the unused one costs nothing
    with_rows = shard_map(
        per_shard, mesh=mesh,
        in_specs=tuple(specs + [P()]),   # indices_rows, replicated
        out_specs=outs,
        check_vma=False)
    without_rows = shard_map(
        per_shard, mesh=mesh,
        in_specs=tuple(specs),
        out_specs=outs,
        check_vma=False)
    dn = (0,) if donate else ()
    jitted_rows = jax.jit(with_rows, donate_argnums=dn)
    jitted = jax.jit(without_rows, donate_argnums=dn)
    checked = set()

    # validate the optional arg up front so a mismatch is a clear
    # TypeError, not an opaque shard_map/jit arity failure
    def step(state, feat, forder, indptr, indices, seeds, labels, key,
             indices_rows=None):
        _check_rows(method, indices_rows, "e2e")
        if indices_rows is not None:
            args = (feat, forder, indptr, indices, seeds, labels, key,
                    indices_rows)
            fn = jitted_rows
        else:
            args = (feat, forder, indptr, indices, seeds, labels, key)
            fn = jitted
        if donate:
            _check_donatable("build_e2e_train_step", fn, checked, state,
                             *args)
        return fn(state, *args)

    step.jitted_fns = (jitted_rows, jitted)
    return step


def build_split_train_step(model, tx, sizes: Sequence[int], batch_size: int,
                           loss_fn: Callable = cross_entropy_logits,
                           method: str = "exact",
                           indices_stride: int | None = None,
                           hub_frac: float | None = None,
                           donate: bool = True):
    """Two-phase step for tiered feature stores (the reference's own
    architecture: sampling and feature collection run as separate stages
    around the model, examples/pyg/reddit_quiver.py:116-122):

      sample_fn(indptr, indices, seeds, key[, indices_rows]) -> (n_id, adjs)
      step_fn(state, x, adjs, labels, key) -> (state, loss)

    Use when features live partly on host/disk: sample on device, fetch
    ``x = feature[n_id]`` through the tiered store (give the store
    ``dedup_cold=True`` so the host tier is read once per unique cold
    node; pair with ``Feature.prefetch`` / ``quiver_tpu.pipeline`` so
    batch i+1's staging overlaps step i), then run the fused
    forward/backward/update. ``sample_fn``'s inputs (topology, seeds)
    are reused across steps, so nothing there is donatable.
    """
    sizes = list(sizes)

    @jax.jit
    def sample_fn(indptr, indices, seeds, key, indices_rows=None):
        # same batch contract as _fused_loss: distinct valid ids,
        # -1 padding at the tail only (labels are position-indexed)
        n_id, layers = sample_multihop(
            indptr, indices, seeds, sizes, key, method=method,
            indices_rows=indices_rows,
            indices_stride=indices_stride if indices_rows is not None
            else None, seeds_dense=True, hub_frac=hub_frac)
        return n_id, layers_to_adjs(layers, batch_size, sizes)

    def step_fn_raw(state: TrainState, x, adjs, labels, key):
        def loss_of(p):
            logits = model.apply(p, x, adjs, train=True,
                                 rngs={"dropout": key})
            return loss_fn(logits[:batch_size], labels)

        loss, grads = jax.value_and_grad(loss_of)(state.params)
        updates, opt_state = tx.update(grads, state.opt_state, state.params)
        params = optax.apply_updates(state.params, updates)
        return TrainState(params, opt_state, state.step + 1), loss

    jitted = jax.jit(step_fn_raw, donate_argnums=(0,) if donate else ())
    if not donate:
        return sample_fn, jitted
    checked = set()

    def step_fn(state, *args, **kwargs):
        _check_donatable("build_split_train_step", jitted, checked, state,
                         *args, **kwargs)
        return jitted(state, *args, **kwargs)

    return sample_fn, step_fn


def init_state(model, tx, example_x, example_adjs, key) -> TrainState:
    params = model.init(key, example_x, example_adjs)
    return TrainState(params, tx.init(params), jnp.zeros((), jnp.int32))


# the donation contract is identical across the step builders — stamp
# it onto each docstring once instead of drifting three copies
# (guarded: under python -OO docstrings are None)
for _b in (build_train_step, build_e2e_train_step, build_split_train_step):
    if _b.__doc__:
        _b.__doc__ += _DONATED_DOC
# likewise for the collect_metrics contract (split step: no knob — its
# stages are driven from the host, where StepStats times them directly)
for _b in (build_train_step, build_e2e_train_step):
    if _b.__doc__:
        _b.__doc__ += _COLLECT_DOC
del _b
