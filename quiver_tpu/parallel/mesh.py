"""Mesh construction helpers.

The reference's device topology is probed NVLink cliques + NCCL ranks
(survey §2.1 P2, §2.2 N8/N9); the TPU-native equivalent is just a named
`jax.sharding.Mesh` whose axes carry the parallelism meaning:

- ``data``  : data parallelism (per-chip seed batches; grads psum)
- ``cache`` : feature-store row sharding (the "p2p clique" generalization)

Both can map onto the same physical axis for small meshes.
"""

from __future__ import annotations

from typing import Optional, Sequence

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P


def make_mesh(axis_names: Sequence[str] = ("data",),
              shape: Optional[Sequence[int]] = None,
              devices=None) -> Mesh:
    devices = list(devices if devices is not None else jax.devices())
    if shape is None:
        shape = [len(devices)] + [1] * (len(axis_names) - 1)
    arr = np.array(devices).reshape(tuple(shape))
    return Mesh(arr, axis_names=tuple(axis_names))


def replicated(mesh: Mesh) -> NamedSharding:
    return NamedSharding(mesh, P())


def row_sharded(mesh: Mesh, axis: str) -> NamedSharding:
    return NamedSharding(mesh, P(axis))
