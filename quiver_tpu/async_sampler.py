"""Per-layer sampler with explicit sample/reindex steps.

Capability parity with the reference's ``AsyncCudaNeighborSampler``
(async_cuda_sampler.py:24-58) — the legacy per-layer API where the caller
drives ``sample_layer`` and ``reindex`` itself (the reference version is
bit-rotted against stale binding names; this one is wired to the live
ops). On TPU "async" is the default: every call is dispatched
asynchronously and only materializes on use.
"""

from __future__ import annotations


import jax
import jax.numpy as jnp

from .ops.sample import compact_layer, sample_layer
from .utils import CSRTopo


class AsyncNeighborSampler:
    def __init__(self, csr_topo: CSRTopo, device=None, seed: int = 0):
        self.csr_topo = csr_topo
        self.device = device
        self._key = jax.random.key(seed)
        self._indptr = jnp.asarray(csr_topo.indptr)
        self._indices = jnp.asarray(csr_topo.indices)

    def next_key(self):
        self._key, sub = jax.random.split(self._key)
        return sub

    def sample_layer(self, batch, size: int):
        """(neighbors [bs, size] -1-filled, counts [bs])."""
        seeds = jnp.asarray(batch, jnp.int32)
        return sample_layer(self._indptr, self._indices, seeds, size,
                            self.next_key())

    def reindex(self, inputs, outputs, counts=None):
        """(n_id, row, col) of the layer's bipartite graph, compacted."""
        layer = compact_layer(jnp.asarray(inputs, jnp.int32),
                              jnp.asarray(outputs, jnp.int32))
        return layer.n_id, layer.row, layer.col


# reference-compatible alias
AsyncCudaNeighborSampler = AsyncNeighborSampler
