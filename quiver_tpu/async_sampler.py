"""Per-layer sampler with explicit sample/reindex steps.

Capability parity with the reference's ``AsyncCudaNeighborSampler``
(async_cuda_sampler.py:24-58) — the legacy per-layer API where the caller
drives ``sample_layer`` and ``reindex`` itself (the reference version is
bit-rotted against stale binding names; this one is wired to the live
ops). On TPU "async" is the default: every call is dispatched
asynchronously and only materializes on use.
"""

from __future__ import annotations


import jax
import jax.numpy as jnp

from .ops.sample import compact_layer, sample_layer
from .utils import CSRTopo


class AsyncNeighborSampler:
    def __init__(self, csr_topo: CSRTopo, device=None, seed: int = 0):
        self.csr_topo = csr_topo
        self.device = device
        self._key = jax.random.key(seed)
        self._indptr = jnp.asarray(csr_topo.indptr)
        self._indices = jnp.asarray(csr_topo.indices)

    def next_key(self):
        self._key, sub = jax.random.split(self._key)
        return sub

    def sample_layer(self, batch, size: int):
        """(neighbors [bs, size] -1-filled, counts [bs])."""
        seeds = jnp.asarray(batch, jnp.int32)
        return sample_layer(self._indptr, self._indices, seeds, size,
                            self.next_key())

    def reindex(self, inputs, outputs, counts=None):
        """(n_id, row, col) of the layer's bipartite graph, compacted."""
        layer = compact_layer(jnp.asarray(inputs, jnp.int32),
                              jnp.asarray(outputs, jnp.int32))
        return layer.n_id, layer.row, layer.col


def sample_ahead(sampler, seed_batches, feature=None, depth: int = 2):
    """Drive ``sampler.sample`` ONE batch ahead on a bounded
    :class:`~quiver_tpu.pipeline.Pipeline`, publishing each sampled
    batch's frontier to ``feature``'s cold-tier prefetcher the moment
    the sample completes — the sampler side of the frontier-ahead
    disk-prefetch loop (see ``quiver_tpu.prefetch``).

    Yields ``sampler.sample(seeds)`` results in submission order. With
    ``depth=2`` (double-buffer), while the caller consumes batch *i*
    (gathers features, runs the model step), batch *i+1* is sampling on
    the pipeline worker and — as soon as its frontier ids exist —
    published via ``feature.stage_frontier(n_id)``, so the prefetcher's
    disk read overlaps batch *i*'s compute. The publication happens on
    the worker thread: a device-array frontier blocks *there*, never
    the training loop. ``feature=None`` degenerates to plain
    sample-ahead pipelining (no publication).

    ::

        pf = store.enable_cold_prefetch(capacity_rows=1 << 16)
        for n_id, bs, adjs in sample_ahead(sampler, seeds, store):
            x = store[n_id]           # staged rows: no disk stall
            state, loss = step(state, x, adjs, ...)
    """
    from .pipeline import Pipeline
    pipe = Pipeline(depth=depth, name="quiver-sample-ahead")

    def _stage(seeds):
        out = sampler.sample(seeds)
        if feature is not None:
            # out[0] is the batch's n_id: hop-0 seeds + every sampled
            # hop's ids — exactly the frontier the gather will request
            feature.stage_frontier(out[0])
        return out

    try:
        yield from pipe.map(_stage, seed_batches)
    finally:
        pipe.close()


# reference-compatible alias
AsyncCudaNeighborSampler = AsyncNeighborSampler
