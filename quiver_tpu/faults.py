"""Deterministic fault injection: failure as a first-class test input.

Every resilience claim in this package — "misses are counted, never
wrong", "the pipeline stays serviceable after a worker exception",
"a dead replica degrades latency in a planned way" — was, until this
module, exercised only by whatever faults the host happened to supply.
This module makes faults an *injectable, seeded, reproducible* input:

- a :class:`FaultPlan` maps **named sites** (fixed strings threaded
  through the existing layers — see :data:`SITES`) to
  :class:`FaultRule` triggers: fire on the Nth visit (``after``), at a
  seeded probability (``rate`` — ``random.Random(f"{seed}:{site}")``
  per site, NO wall-clock randomness, so two processes armed with the
  same spec fire identically), at most ``times`` times;
- a fired rule raises a typed exception (``OSError`` with a chosen
  ``errno`` for the storage sites, ``RuntimeError`` elsewhere),
  sleeps (``delay``/``hang``), or kills the process (``kill``/
  ``exit`` — the replica-chaos primitives the supervisor tests
  against);
- arming is process-global and **off by default with no hot-path
  cost**: every instrumented site is one ``faults.fire(name)`` call
  whose disarmed body is a single module-global ``None`` check, all
  sites live on host-side control paths (per extent / per batch / per
  request — never per row), and NONE of them is inside a jitted
  program, so the zero-host-sync / bit-identity / flat-executable
  invariants hold by construction (and are pinned with a rate-0 plan
  armed in tests/test_faults.py).

Arm from the environment (what the chaos bench and the supervisor use
to arm child replicas)::

    QT_FAULTS="io.read:error,errno=EIO,rate=0.2,times=3;rpc.request:kill,after=40"
    QT_FAULTS_SEED=7

or in-process::

    plan = FaultPlan(seed=7, rules={"io.read": FaultRule("error",
                                    errno_name="EIO", rate=0.2)})
    faults.install(plan)
    ...
    faults.disarm()

``plan.counts()`` exposes per-site ``{checks, fires}``;
:func:`drain_injected` feeds the ``faults_injected`` metrics slot;
``plan.emit(sink)`` writes one ``chaos`` JSONL record (the seed, the
spec, the per-site counts) so a chaos run's record is self-describing.

Stdlib only — the fake-replica harness loads this file (and ``rpc.py``)
through a synthetic package with no jax import.
"""

from __future__ import annotations

import errno as _errno
import os
import random
import threading
import time
from typing import Dict, Optional

__all__ = ["SITES", "FaultRule", "FaultPlan", "install", "disarm",
           "active", "fire", "drain_injected", "plan_from_env"]

#: The named injection sites threaded through the tree. Adding a site
#: is adding a ``faults.fire("<name>")`` call on a host-side control
#: path plus a row in docs/observability.md's chaos section.
SITES = (
    "io.read",          # ExtentReader: one coalesced-extent read
    "io.slow",          # ExtentReader: delay before an extent read
    "prefetch.stager",  # ColdPrefetcher: one staging shard
    "pipeline.worker",  # Pipeline: worker loop top (thread death)
    "sink.write",       # MetricsSink.emit: the JSONL write
    "serve.coalesce",   # MicroBatchServer: coalescer loop top
    "serve.execute",    # MicroBatchServer: batch execute
    "rpc.request",      # RpcServer: per accepted request
)

_ERRNO_OK = ("EIO", "EINTR", "EAGAIN", "ENOSPC", "EPIPE", "ECONNRESET")


class FaultRule:
    """One site's trigger + effect.

    ``action``: ``error`` (raise), ``delay`` (sleep ``delay_ms`` then
    continue), ``hang`` (sleep ``hang_s``, default 30 — longer than any
    sane deadline), ``kill`` (SIGKILL self), ``exit`` (``os._exit``).
    ``rate`` fires the rule on that fraction of eligible visits (seeded
    per-site RNG; 1.0 = every visit). ``after`` skips the first N
    visits (a deterministic "at request N+1" trigger). ``times`` caps
    total fires (None = unlimited). ``errno_name`` picks the OSError
    errno for ``error`` kind; ``exc="runtime"`` raises RuntimeError
    instead."""

    __slots__ = ("action", "rate", "after", "times", "errno_name",
                 "delay_ms", "hang_s", "exc")

    def __init__(self, action: str = "error", rate: float = 1.0,
                 after: int = 0, times: Optional[int] = None,
                 errno_name: str = "EIO", delay_ms: float = 5.0,
                 hang_s: float = 30.0, exc: str = "oserror"):
        if action not in ("error", "delay", "hang", "kill", "exit"):
            raise ValueError(f"unknown fault action {action!r}")
        if not 0.0 <= float(rate) <= 1.0:
            raise ValueError(f"rate must be in [0, 1], got {rate}")
        if errno_name not in _ERRNO_OK:
            raise ValueError(f"errno must be one of {_ERRNO_OK}, "
                             f"got {errno_name!r}")
        self.action = action
        self.rate = float(rate)
        self.after = int(after)
        self.times = None if times is None else int(times)
        self.errno_name = errno_name
        self.delay_ms = float(delay_ms)
        self.hang_s = float(hang_s)
        self.exc = exc

    def spec(self) -> str:
        """The one-rule half of a ``QT_FAULTS`` spec string."""
        parts = [self.action]
        if self.rate != 1.0:
            parts.append(f"rate={self.rate}")
        if self.after:
            parts.append(f"after={self.after}")
        if self.times is not None:
            parts.append(f"times={self.times}")
        if self.action == "error":
            if self.errno_name != "EIO":
                parts.append(f"errno={self.errno_name}")
            if self.exc != "oserror":
                parts.append(f"exc={self.exc}")
        if self.action == "delay" and self.delay_ms != 5.0:
            parts.append(f"delay_ms={self.delay_ms}")
        if self.action == "hang" and self.hang_s != 30.0:
            parts.append(f"hang_s={self.hang_s}")
        return ",".join(parts)

    def __repr__(self):
        return f"FaultRule({self.spec()})"


class _SiteState:
    __slots__ = ("rng", "checks", "fires")

    def __init__(self, seed: int, site: str):
        self.rng = random.Random(f"{seed}:{site}")
        self.checks = 0
        self.fires = 0


class FaultPlan:
    """A seeded set of site rules (see module doc). Thread-safe; the
    trigger decision runs under one lock, the effect (raise/sleep/kill)
    outside it."""

    def __init__(self, seed: int = 0,
                 rules: Optional[Dict[str, FaultRule]] = None):
        for site in (rules or {}):
            if site not in SITES:
                raise ValueError(f"unknown fault site {site!r} "
                                 f"(known: {SITES})")
        self.seed = int(seed)
        self.rules: Dict[str, FaultRule] = dict(rules or {})
        self._state = {s: _SiteState(self.seed, s) for s in self.rules}
        self._lock = threading.Lock()
        self._injected = 0
        self._drained = 0

    # -- the hot-path check --------------------------------------------------
    def check(self, site: str) -> None:
        rule = self.rules.get(site)
        if rule is None:
            return
        with self._lock:
            st = self._state[site]
            st.checks += 1
            if st.checks <= rule.after:
                return
            if rule.times is not None and st.fires >= rule.times:
                return
            if rule.rate < 1.0 and st.rng.random() >= rule.rate:
                return
            st.fires += 1
            self._injected += 1
        self._fire(site, rule)

    def _fire(self, site: str, rule: FaultRule) -> None:
        if rule.action == "error":
            if rule.exc == "runtime":
                raise RuntimeError(f"injected fault at {site} "
                                   f"(seed {self.seed})")
            code = getattr(_errno, rule.errno_name)
            raise OSError(code, f"injected {rule.errno_name} at {site} "
                                f"(seed {self.seed})")
        if rule.action == "delay":
            time.sleep(rule.delay_ms / 1e3)
            return
        if rule.action == "hang":
            time.sleep(rule.hang_s)
            return
        if rule.action == "kill":
            import signal
            os.kill(os.getpid(), signal.SIGKILL)
            return                       # pragma: no cover (we died)
        os._exit(17)                     # action == "exit"

    # -- accounting ----------------------------------------------------------
    def counts(self) -> Dict[str, Dict[str, int]]:
        """Per-site ``{checks, fires}`` (snapshot)."""
        with self._lock:
            return {s: {"checks": st.checks, "fires": st.fires}
                    for s, st in self._state.items()}

    @property
    def injected(self) -> int:
        with self._lock:
            return self._injected

    def drain(self) -> int:
        """Fires since the last drain — the ``faults_injected`` slot's
        per-interval figure."""
        with self._lock:
            d = self._injected - self._drained
            self._drained = self._injected
            return d

    # -- serialization -------------------------------------------------------
    def spec(self) -> str:
        """The ``QT_FAULTS`` string reproducing this plan (modulo seed,
        which rides ``QT_FAULTS_SEED``) — how the supervisor/bench arm
        child replicas."""
        return ";".join(f"{site}:{rule.spec()}"
                        for site, rule in sorted(self.rules.items()))

    def env(self) -> Dict[str, str]:
        """The env-var pair arming a child process with this plan."""
        return {"QT_FAULTS": self.spec(),
                "QT_FAULTS_SEED": str(self.seed)}

    def snapshot(self) -> dict:
        """JSONL-ready ``chaos`` payload: the plan + what it did."""
        return {"seed": self.seed, "spec": self.spec(),
                "injected": self.injected, "sites": self.counts()}

    def emit(self, sink, kind: str = "chaos") -> dict:
        """Append :meth:`snapshot` to a ``metrics.MetricsSink``."""
        return sink.emit(self.snapshot(), kind=kind)

    def __repr__(self):
        return f"FaultPlan(seed={self.seed}, {self.spec()!r})"


def parse_spec(spec: str, seed: int = 0) -> FaultPlan:
    """Parse a ``QT_FAULTS`` spec string (see module doc) into a plan.
    Format: ``site:action[,key=value...]`` joined by ``;``. Unknown
    sites/actions/keys raise — a typo'd chaos plan silently injecting
    nothing would report "survived" without the test."""
    rules: Dict[str, FaultRule] = {}
    for part in spec.split(";"):
        part = part.strip()
        if not part:
            continue
        if ":" not in part:
            raise ValueError(f"bad QT_FAULTS rule {part!r} "
                             "(want site:action[,k=v...])")
        site, body = part.split(":", 1)
        fields = [f.strip() for f in body.split(",") if f.strip()]
        if not fields:
            raise ValueError(f"bad QT_FAULTS rule {part!r}: no action")
        kw: dict = {"action": fields[0]}
        for f in fields[1:]:
            if "=" not in f:
                raise ValueError(f"bad QT_FAULTS field {f!r} in {part!r}")
            k, v = f.split("=", 1)
            if k == "errno":
                kw["errno_name"] = v
            elif k in ("rate", "delay_ms", "hang_s"):
                kw[k] = float(v)
            elif k in ("after", "times"):
                kw[k] = int(v)
            elif k == "exc":
                kw["exc"] = v
            else:
                raise ValueError(f"unknown QT_FAULTS key {k!r} in {part!r}")
        rules[site.strip()] = FaultRule(**kw)
    return FaultPlan(seed=seed, rules=rules)


def plan_from_env(environ=None) -> Optional[FaultPlan]:
    """The plan ``QT_FAULTS``/``QT_FAULTS_SEED`` describe, or None."""
    env = os.environ if environ is None else environ
    spec = env.get("QT_FAULTS")
    if not spec:
        return None
    return parse_spec(spec, seed=int(env.get("QT_FAULTS_SEED", "0")))


# -- process-global arming ----------------------------------------------------

_PLAN: Optional[FaultPlan] = None


def install(plan: FaultPlan) -> FaultPlan:
    """Arm ``plan`` process-wide (replaces any previous plan)."""
    global _PLAN
    _PLAN = plan
    return plan


def disarm() -> None:
    """Disarm: every ``fire()`` is a no-op again."""
    global _PLAN
    _PLAN = None


def active() -> Optional[FaultPlan]:
    """The armed plan, or None."""
    return _PLAN


def fire(site: str) -> None:
    """The site hook the instrumented layers call. Disarmed (the
    default), this is one global load + None check."""
    p = _PLAN
    if p is not None:
        p.check(site)


def drain_injected() -> int:
    """Fires since the last drain across the armed plan (0 when
    disarmed) — what the metered lookup writes into the
    ``faults_injected`` counter slot."""
    p = _PLAN
    return 0 if p is None else p.drain()


# arm from the environment at import: QT_FAULTS is how the chaos bench
# and the supervisor arm whole child processes without code changes
_env_plan = plan_from_env()
if _env_plan is not None and _env_plan.rules:
    install(_env_plan)
del _env_plan
