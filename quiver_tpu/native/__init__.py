"""ctypes loader for the native host sampling engine.

Compiles ``cpu_sampler.cpp`` on first use (g++ -O3 -shared) and exposes
numpy-facing wrappers. Falls back to a pure-numpy implementation when no
compiler is available, so the package stays importable everywhere.

Replaces the reference's torch C++ extension boundary for the CPU path
(srcs/cpp/src/quiver/quiver.cpp:11-119) — ctypes instead of pybind11.
"""

from __future__ import annotations

import ctypes
import os
import subprocess
import tempfile
import threading
from typing import List, Optional, Sequence, Tuple

import numpy as np

_HERE = os.path.dirname(os.path.abspath(__file__))
_SRC = os.path.join(_HERE, "cpu_sampler.cpp")
#: bump together with the qt_abi_vN gate in _bind(); the filename is
#: ABI-versioned so a .so built for an older ABI is simply never found
#: (vs silently binding and failing the gate)
_ABI = 2
_LIB_PATH = os.path.join(_HERE, f"_cpu_sampler_v{_ABI}.so")
_lock = threading.Lock()
_lib: Optional[ctypes.CDLL] = None
_build_failed = False


def _build(dst: str) -> Optional[str]:
    """Compile the engine to ``dst``. The compile goes to a scratch file
    first and lands via os.replace, so a concurrent process that already
    mapped an old ``dst`` keeps its (old-inode) image instead of having
    a live ELF truncated under it."""
    tmp = f"{dst}.tmp.{os.getpid()}"
    cmd = ["g++", "-O3", "-march=native", "-shared", "-fPIC", "-std=c++17",
           "-pthread", _SRC, "-o", tmp]
    for attempt in (cmd, [c for c in cmd if c != "-march=native"]):
        # second attempt drops -march=native (some toolchains lack it)
        try:
            subprocess.run(attempt, check=True, capture_output=True,
                           timeout=120)
            os.replace(tmp, dst)
            return dst
        except (OSError, subprocess.SubprocessError):
            continue
    try:
        os.unlink(tmp)
    except OSError:
        pass
    return None


def _fresh_lib_path() -> str:
    """A never-before-dlopened filename for rebuild recovery: glibc
    dedupes dlopen by pathname, so re-CDLLing a rebuilt ``_LIB_PATH``
    would just rebind the stale image already mapped in this process.
    Building under a fresh name sidesteps the cache entirely. Prefer
    the package dir (where the canonical .so demonstrably dlopens —
    system tempdirs are often mounted noexec); fall back to the
    tempdir only when the package dir is unwritable."""
    try:
        fd, path = tempfile.mkstemp(prefix=f"_cpu_sampler_v{_ABI}_",
                                    suffix=".so", dir=_HERE)
    except OSError:
        fd, path = tempfile.mkstemp(prefix=f"_cpu_sampler_v{_ABI}_",
                                    suffix=".so")
    os.close(fd)
    return path


def get_lib() -> Optional[ctypes.CDLL]:
    global _lib, _build_failed
    if _lib is not None or _build_failed:
        return _lib
    with _lock:
        if _lib is not None or _build_failed:
            return _lib
        have_so = os.path.exists(_LIB_PATH)
        stale = (not have_so
                 or (os.path.exists(_SRC)
                     and os.path.getmtime(_LIB_PATH) < os.path.getmtime(_SRC)))
        path = _build(_LIB_PATH) if stale else _LIB_PATH
        if path is None and have_so:
            path = _LIB_PATH        # no compiler: try the prebuilt .so
        if path is None:
            _build_failed = True
            return None
        try:
            lib = _bind(ctypes.CDLL(path))
        except (OSError, AttributeError):
            # the .so at the canonical path is stale or corrupt AND this
            # process may already have it mapped — rebuild under a fresh
            # filename and load THAT (see _fresh_lib_path); also repair
            # the canonical path for future processes
            fresh = _fresh_lib_path()
            try:
                path = _build(fresh)
                if path is None:
                    _build_failed = True
                    return None
                try:
                    lib = _bind(ctypes.CDLL(path))
                except (OSError, AttributeError):
                    _build_failed = True
                    return None
                try:  # future processes get the good build here
                    import shutil
                    shutil.copy(path, _LIB_PATH + f".tmp.{os.getpid()}")
                    os.replace(_LIB_PATH + f".tmp.{os.getpid()}",
                               _LIB_PATH)
                except OSError:
                    pass
            finally:
                try:  # a live mapping keeps its inode; drop the dirent
                    os.unlink(fresh)
                except OSError:
                    pass
        _lib = lib
        return _lib


def _bind(lib: ctypes.CDLL) -> ctypes.CDLL:
    # ABI gate: raises AttributeError on a stale .so whose
    # qt_sample_layer* still have the pre-out_slots signatures (the
    # names alone would bind and silently return garbage slots);
    # get_lib()'s except-path then rebuilds or falls back to numpy
    lib.qt_abi_v2
    lib.qt_sample_layer.argtypes = [
        ctypes.POINTER(ctypes.c_int64), ctypes.POINTER(ctypes.c_int32),
        ctypes.POINTER(ctypes.c_int32), ctypes.c_int64, ctypes.c_int32,
        ctypes.c_uint64, ctypes.POINTER(ctypes.c_int32),
        ctypes.POINTER(ctypes.c_int32), ctypes.POINTER(ctypes.c_int64),
        ctypes.c_int32,
    ]
    lib.qt_sample_layer.restype = None
    lib.qt_sample_layer_weighted.argtypes = [
        ctypes.POINTER(ctypes.c_int64), ctypes.POINTER(ctypes.c_int32),
        ctypes.POINTER(ctypes.c_float), ctypes.POINTER(ctypes.c_int32),
        ctypes.c_int64, ctypes.c_int32, ctypes.c_int32, ctypes.c_uint64,
        ctypes.POINTER(ctypes.c_int32), ctypes.POINTER(ctypes.c_int32),
        ctypes.POINTER(ctypes.c_int64), ctypes.c_int32,
    ]
    lib.qt_sample_layer_weighted.restype = None
    lib.qt_reindex.argtypes = [
        ctypes.POINTER(ctypes.c_int32), ctypes.c_int64,
        ctypes.POINTER(ctypes.c_int32), ctypes.c_int32,
        ctypes.POINTER(ctypes.c_int32), ctypes.POINTER(ctypes.c_int32),
        ctypes.POINTER(ctypes.c_int32),
    ]
    lib.qt_reindex.restype = ctypes.c_int64
    return lib


def cpu_reindex(seeds: np.ndarray, nbrs: np.ndarray
                ) -> Tuple[np.ndarray, int, np.ndarray, np.ndarray]:
    """First-occurrence hop compaction on the host (C++ hash table, numpy
    fallback). seeds [s] (-1 ok), nbrs [s, k] (-1 fill).
    Returns (n_id [s + s*k] -1-filled, count, row [s*k], col [s*k])."""
    seeds = np.ascontiguousarray(seeds, dtype=np.int32)
    nbrs = np.ascontiguousarray(nbrs, dtype=np.int32)
    s, k = nbrs.shape
    cap = s + s * k
    n_id = np.empty((cap,), np.int32)
    row = np.empty((s * k,), np.int32)
    col = np.empty((s * k,), np.int32)
    lib = get_lib()
    if lib is not None:
        count = lib.qt_reindex(
            _ptr(seeds, ctypes.c_int32), s, _ptr(nbrs, ctypes.c_int32), k,
            _ptr(n_id, ctypes.c_int32), _ptr(row, ctypes.c_int32),
            _ptr(col, ctypes.c_int32))
        return n_id, int(count), row, col
    # numpy fallback: vectorized first-occurrence unique (stable argsort
    # of first-occurrence positions), same contract as the C++ path
    # neighbors of invalid (-1) seeds carry no edges and must not enter
    # the unique set (matches the C++ path)
    nbr_masked = np.where(np.repeat(seeds >= 0, k), nbrs.reshape(-1), -1)
    flat = np.concatenate([seeds, nbr_masked])
    valid = flat >= 0
    vals, first_idx = np.unique(flat[valid], return_index=True)
    order = np.argsort(np.flatnonzero(valid)[first_idx], kind="stable")
    uniq = vals[order]                       # first-occurrence order
    count = int(uniq.shape[0])
    rank_to_local = np.empty_like(order, dtype=np.int32)
    rank_to_local[order] = np.arange(count, dtype=np.int32)
    n_id[:] = -1
    n_id[:count] = uniq
    safe = np.where(valid, flat, vals[0] if count else 0)
    local_all = rank_to_local[np.searchsorted(vals, safe)] if count else \
        np.zeros_like(flat)
    seed_local = np.where(seeds >= 0, local_all[:s], -1)
    nbr_flat = nbr_masked
    edge_ok = (nbr_flat >= 0) & np.repeat(seed_local >= 0, k)
    row[:] = np.where(edge_ok, np.repeat(seed_local, k), -1)
    col[:] = np.where(edge_ok, local_all[s:], -1)
    return n_id, count, row, col


def _ptr(arr, ctype):
    return arr.ctypes.data_as(ctypes.POINTER(ctype))


def cpu_sample_layer(indptr: np.ndarray, indices: np.ndarray,
                     seeds: np.ndarray, k: int, seed: int = 0,
                     num_threads: int = 0, with_slots: bool = False):
    """Per seed: up to k distinct uniform neighbors. Returns
    (nbrs [s, k] -1 fill, counts); with ``with_slots`` additionally
    each pick's flat CSR slot ([s, k] int64, -1 fill) — the input to
    edge-id lookups, mirroring the device samplers."""
    indptr = np.ascontiguousarray(indptr, dtype=np.int64)
    indices = np.ascontiguousarray(indices, dtype=np.int32)
    seeds = np.ascontiguousarray(seeds, dtype=np.int32)
    s = seeds.shape[0]
    nbrs = np.empty((s, k), dtype=np.int32)
    counts = np.empty((s,), dtype=np.int32)
    slots = np.empty((s, k), dtype=np.int64) if with_slots else None
    lib = get_lib()
    if lib is not None:
        lib.qt_sample_layer(
            _ptr(indptr, ctypes.c_int64), _ptr(indices, ctypes.c_int32),
            _ptr(seeds, ctypes.c_int32), s, k, seed & (2 ** 64 - 1),
            _ptr(nbrs, ctypes.c_int32), _ptr(counts, ctypes.c_int32),
            None if slots is None else _ptr(slots, ctypes.c_int64),
            num_threads)
        return (nbrs, counts, slots) if with_slots else (nbrs, counts)
    return _numpy_sample_layer(indptr, indices, seeds, k, seed,
                               with_slots=with_slots)


def cpu_sample_layer_weighted(indptr: np.ndarray, indices: np.ndarray,
                              weights: np.ndarray, seeds: np.ndarray,
                              k: int, seed: int = 0, row_cap: int = 2048,
                              num_threads: int = 0,
                              with_slots: bool = False):
    """Per seed: k draws WITH replacement ~ edge weight among the first
    min(deg, row_cap) neighbors — the device contract
    (ops/weighted.py), so host and device batches interleave with
    identical distributions. Returns (nbrs [s, k] -1 fill, counts
    = min(deg, k), 0 for zero-mass rows — which come back fully
    masked, like the device path)."""
    indptr = np.ascontiguousarray(indptr, dtype=np.int64)
    indices = np.ascontiguousarray(indices, dtype=np.int32)
    weights = np.ascontiguousarray(weights, dtype=np.float32)
    seeds = np.ascontiguousarray(seeds, dtype=np.int32)
    s = seeds.shape[0]
    nbrs = np.empty((s, k), dtype=np.int32)
    counts = np.empty((s,), dtype=np.int32)
    slots = np.empty((s, k), dtype=np.int64) if with_slots else None
    lib = get_lib()
    if lib is not None:
        lib.qt_sample_layer_weighted(
            _ptr(indptr, ctypes.c_int64), _ptr(indices, ctypes.c_int32),
            _ptr(weights, ctypes.c_float), _ptr(seeds, ctypes.c_int32),
            s, k, row_cap, seed & (2 ** 64 - 1),
            _ptr(nbrs, ctypes.c_int32), _ptr(counts, ctypes.c_int32),
            None if slots is None else _ptr(slots, ctypes.c_int64),
            num_threads)
        return (nbrs, counts, slots) if with_slots else (nbrs, counts)
    return _numpy_sample_layer_weighted(indptr, indices, weights, seeds,
                                        k, seed, row_cap,
                                        with_slots=with_slots)


def _numpy_sample_layer_weighted(indptr, indices, weights, seeds, k, seed,
                                 row_cap, with_slots=False):
    rng = np.random.default_rng(seed)
    s = seeds.shape[0]
    nbrs = np.full((s, k), -1, dtype=np.int32)
    counts = np.zeros((s,), dtype=np.int32)
    slots = np.full((s, k), -1, dtype=np.int64) if with_slots else None
    for i, v in enumerate(seeds):
        if v < 0:
            continue
        lo, hi = indptr[v], indptr[v + 1]
        deg = int(hi - lo)
        pool = min(deg, row_cap)
        w = np.clip(weights[lo:lo + pool].astype(np.float64), 0.0, None)
        total = w.sum()
        if total <= 0.0 or min(deg, k) == 0:
            continue            # zero-mass/empty row: counts stays 0
        counts[i] = min(deg, k)
        picks = rng.choice(pool, size=counts[i], replace=True, p=w / total)
        nbrs[i, :counts[i]] = indices[lo + picks]
        if slots is not None:
            slots[i, :counts[i]] = lo + picks
    return (nbrs, counts, slots) if with_slots else (nbrs, counts)


def _numpy_sample_layer(indptr, indices, seeds, k, seed, with_slots=False):
    rng = np.random.default_rng(seed)
    s = seeds.shape[0]
    nbrs = np.full((s, k), -1, dtype=np.int32)
    counts = np.zeros((s,), dtype=np.int32)
    slots = np.full((s, k), -1, dtype=np.int64) if with_slots else None
    for i, v in enumerate(seeds):
        if v < 0:
            continue
        lo = indptr[v]
        row = indices[lo:indptr[v + 1]]
        c = min(len(row), k)
        counts[i] = c
        if c == len(row):
            picks = np.arange(c)
        else:
            picks = rng.choice(len(row), size=c, replace=False)
        nbrs[i, :c] = row[picks]
        if slots is not None:
            slots[i, :c] = lo + picks
    return (nbrs, counts, slots) if with_slots else (nbrs, counts)


def cpu_sample_multihop(indptr, indices, seeds: np.ndarray,
                        sizes: Sequence[int], seed: int = 0,
                        num_threads: int = 0, weights=None,
                        row_cap: int = 2048, with_slots: bool = False):
    """Host mirror of the device multi-hop sampler: identical shapes
    (static caps, -1 fill) so results interleave freely with device
    output. With ``weights`` (CSR-slot-aligned), every hop draws
    weighted-with-replacement like the device's edge_weight path.
    Returns (n_id, rows, cols); with ``with_slots`` additionally a
    per-hop list of flat CSR slots ([s*k] int64, -1 fill, aligned with
    rows/cols) — the input to edge-id lookups.
    """
    indptr = np.ascontiguousarray(indptr, dtype=np.int64)
    indices = np.ascontiguousarray(indices, dtype=np.int32)
    cur = np.ascontiguousarray(seeds, dtype=np.int32)
    rows, cols, slot_lists = [], [], []
    for li, k in enumerate(sizes):
        if weights is not None:
            out = cpu_sample_layer_weighted(
                indptr, indices, weights, cur, k, seed=seed + li,
                row_cap=row_cap, num_threads=num_threads,
                with_slots=with_slots)
        else:
            out = cpu_sample_layer(
                indptr, indices, cur, k, seed=seed + li,
                num_threads=num_threads, with_slots=with_slots)
        nbrs = out[0]
        slots = out[2] if with_slots else None
        n_id, _count, row, col = cpu_reindex(cur, nbrs)
        rows.append(row)
        cols.append(col)
        if with_slots:
            # an edge masked during reindex (invalid seed) must mask
            # its slot with it
            slot_lists.append(np.where(col >= 0, slots.reshape(-1), -1))
        cur = n_id
    if with_slots:
        return cur, rows, cols, slot_lists
    return cur, rows, cols
