// Native host-side neighbor sampling engine.
//
// TPU-native equivalent of the reference CPU sampling engine
// quiver<T,CPU> (srcs/cpp/include/quiver/quiver.cpu.hpp:30-103): parallel
// per-seed uniform without-replacement neighbor sampling over CSR. Feeds
// the hybrid host+device sampling path (MixedGraphSageSampler) while the
// TPU runs the jitted device sampler.
//
// Design differences from the reference: no libtorch/at::parallel_for
// dependency (plain std::thread), partial Fisher-Yates with an O(k) write
// log instead of std::sample (same distribution, no per-row O(deg) temp),
// splitmix64 counter RNG keyed by (seed, row) for reproducibility.

#include <algorithm>
#include <cstdint>
#include <cstring>
#include <thread>
#include <vector>

namespace {

inline uint64_t splitmix64(uint64_t &state) {
    uint64_t z = (state += 0x9E3779B97F4A7C15ULL);
    z = (z ^ (z >> 30)) * 0xBF58476D1CE4E5B9ULL;
    z = (z ^ (z >> 27)) * 0x94D049BB133111EBULL;
    return z ^ (z >> 31);
}

void sample_range(const int64_t *indptr, const int32_t *indices,
                  const int32_t *seeds, int64_t lo, int64_t hi, int32_t k,
                  uint64_t seed, int32_t *out_nbrs, int32_t *out_counts) {
    std::vector<int64_t> pos(k), val(k);
    for (int64_t i = lo; i < hi; ++i) {
        int32_t *out = out_nbrs + i * k;
        const int32_t v = seeds[i];
        if (v < 0) {
            out_counts[i] = 0;
            std::fill(out, out + k, -1);
            continue;
        }
        const int64_t row_start = indptr[v];
        const int64_t deg = indptr[v + 1] - row_start;
        const int64_t c = std::min<int64_t>(deg, k);
        out_counts[i] = static_cast<int32_t>(c);
        if (deg <= k) {
            for (int64_t t = 0; t < deg; ++t) out[t] = indices[row_start + t];
            std::fill(out + deg, out + k, -1);
            continue;
        }
        uint64_t state = seed ^ (0xD1B54A32D192ED03ULL * (uint64_t)(v + 1));
        int written = 0;
        for (int32_t t = 0; t < k; ++t) {
            const int64_t j =
                t + (int64_t)(splitmix64(state) % (uint64_t)(deg - t));
            int64_t a_j = j, a_t = t;
            for (int w = written - 1; w >= 0; --w)
                if (pos[w] == j) { a_j = val[w]; break; }
            for (int w = written - 1; w >= 0; --w)
                if (pos[w] == t) { a_t = val[w]; break; }
            out[t] = indices[row_start + a_j];
            pos[written] = j;
            val[written] = a_t;
            ++written;
        }
    }
}

}  // namespace

extern "C" {

// Sample up to k neighbors (uniform, without replacement) per seed.
// out_nbrs: [num_seeds * k] (-1 fill), out_counts: [num_seeds].
void qt_sample_layer(const int64_t *indptr, const int32_t *indices,
                     const int32_t *seeds, int64_t num_seeds, int32_t k,
                     uint64_t seed, int32_t *out_nbrs, int32_t *out_counts,
                     int32_t num_threads) {
    if (num_seeds == 0) return;
    int32_t nt = num_threads > 0
                     ? num_threads
                     : (int32_t)std::thread::hardware_concurrency();
    nt = std::max(1, std::min<int32_t>(nt, (int32_t)num_seeds));
    if (nt == 1) {
        sample_range(indptr, indices, seeds, 0, num_seeds, k, seed, out_nbrs,
                     out_counts);
        return;
    }
    std::vector<std::thread> threads;
    const int64_t chunk = (num_seeds + nt - 1) / nt;
    for (int32_t t = 0; t < nt; ++t) {
        const int64_t lo = t * chunk;
        const int64_t hi = std::min(num_seeds, lo + chunk);
        if (lo >= hi) break;
        threads.emplace_back(sample_range, indptr, indices, seeds, lo, hi, k,
                             seed, out_nbrs, out_counts);
    }
    for (auto &th : threads) th.join();
}

// Full-row degree lookup (== quiver::degree, quiver.cpu.hpp).
void qt_degree(const int64_t *indptr, const int32_t *seeds, int64_t num_seeds,
               int32_t *out_deg) {
    for (int64_t i = 0; i < num_seeds; ++i) {
        const int32_t v = seeds[i];
        out_deg[i] =
            v < 0 ? 0 : static_cast<int32_t>(indptr[v + 1] - indptr[v]);
    }
}

}  // extern "C"
