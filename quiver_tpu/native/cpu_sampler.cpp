// Native host-side neighbor sampling engine.
//
// TPU-native equivalent of the reference CPU sampling engine
// quiver<T,CPU> (srcs/cpp/include/quiver/quiver.cpu.hpp:30-103): parallel
// per-seed uniform without-replacement neighbor sampling over CSR. Feeds
// the hybrid host+device sampling path (MixedGraphSageSampler) while the
// TPU runs the jitted device sampler.
//
// Design differences from the reference: no libtorch/at::parallel_for
// dependency (plain std::thread), partial Fisher-Yates with an O(k) write
// log instead of std::sample (same distribution, no per-row O(deg) temp),
// splitmix64 counter RNG keyed by (seed, row) for reproducibility.

#include <algorithm>
#include <cstdint>
#include <cstring>
#include <thread>
#include <vector>

namespace {

inline uint64_t splitmix64(uint64_t &state) {
    uint64_t z = (state += 0x9E3779B97F4A7C15ULL);
    z = (z ^ (z >> 30)) * 0xBF58476D1CE4E5B9ULL;
    z = (z ^ (z >> 27)) * 0x94D049BB133111EBULL;
    return z ^ (z >> 31);
}

void sample_range(const int64_t *indptr, const int32_t *indices,
                  const int32_t *seeds, int64_t lo, int64_t hi, int32_t k,
                  uint64_t seed, int32_t *out_nbrs, int32_t *out_counts,
                  int64_t *out_slots) {
    // out_slots (nullable): each pick's flat CSR slot (-1 fill) — the
    // input to edge-id lookups, mirroring the device samplers'
    // with_slots outputs.
    std::vector<int64_t> pos(k), val(k);
    for (int64_t i = lo; i < hi; ++i) {
        int32_t *out = out_nbrs + i * k;
        int64_t *slots = out_slots ? out_slots + i * k : nullptr;
        const int32_t v = seeds[i];
        if (v < 0) {
            out_counts[i] = 0;
            std::fill(out, out + k, -1);
            if (slots) std::fill(slots, slots + k, (int64_t)-1);
            continue;
        }
        const int64_t row_start = indptr[v];
        const int64_t deg = indptr[v + 1] - row_start;
        const int64_t c = std::min<int64_t>(deg, k);
        out_counts[i] = static_cast<int32_t>(c);
        if (deg <= k) {
            for (int64_t t = 0; t < deg; ++t) out[t] = indices[row_start + t];
            std::fill(out + deg, out + k, -1);
            if (slots) {
                for (int64_t t = 0; t < deg; ++t) slots[t] = row_start + t;
                std::fill(slots + deg, slots + k, (int64_t)-1);
            }
            continue;
        }
        uint64_t state = seed ^ (0xD1B54A32D192ED03ULL * (uint64_t)(v + 1));
        int written = 0;
        for (int32_t t = 0; t < k; ++t) {
            const int64_t j =
                t + (int64_t)(splitmix64(state) % (uint64_t)(deg - t));
            int64_t a_j = j, a_t = t;
            for (int w = written - 1; w >= 0; --w)
                if (pos[w] == j) { a_j = val[w]; break; }
            for (int w = written - 1; w >= 0; --w)
                if (pos[w] == t) { a_t = val[w]; break; }
            out[t] = indices[row_start + a_j];
            if (slots) slots[t] = row_start + a_j;
            pos[written] = j;
            val[written] = a_t;
            ++written;
        }
    }
}

void sample_range_weighted(const int64_t *indptr, const int32_t *indices,
                           const float *weights, const int32_t *seeds,
                           int64_t lo, int64_t hi, int32_t k,
                           int32_t row_cap, uint64_t seed,
                           int32_t *out_nbrs, int32_t *out_counts,
                           int64_t *out_slots) {
    // k draws WITH replacement proportional to edge weight, among the
    // first min(deg, row_cap) neighbors — the device contract
    // (ops/weighted.py sample_layer_weighted, itself mirroring the
    // reference weight_sample, cuda_random.cu.hpp:178-221). row_cap
    // matches the device default so host and device batches interleave
    // with identical distributions in the mixed sampler.
    std::vector<double> cdf(row_cap);
    for (int64_t i = lo; i < hi; ++i) {
        int32_t *out = out_nbrs + i * k;
        const int32_t v = seeds[i];
        if (v < 0) {
            out_counts[i] = 0;
            std::fill(out, out + k, -1);
            if (out_slots)
                std::fill(out_slots + i * k, out_slots + (i + 1) * k,
                          (int64_t)-1);
            continue;
        }
        const int64_t row_start = indptr[v];
        const int64_t deg = indptr[v + 1] - row_start;
        const int64_t pool = std::min<int64_t>(deg, row_cap);
        double total = 0.0;
        for (int64_t t = 0; t < pool; ++t) {
            const float w = weights[row_start + t];
            total += w > 0.0f ? (double)w : 0.0;
            cdf[t] = total;
        }
        if (total <= 0.0) {
            // zero-mass row: fully masked AND counts = 0 — the device
            // contract (ops/weighted.py zeroes counts when total <= 0)
            out_counts[i] = 0;
            std::fill(out, out + k, -1);
            if (out_slots)
                std::fill(out_slots + i * k, out_slots + (i + 1) * k,
                          (int64_t)-1);
            continue;
        }
        out_counts[i] = static_cast<int32_t>(std::min<int64_t>(deg, k));
        uint64_t state = seed ^ (0xD1B54A32D192ED03ULL * (uint64_t)(v + 1));
        for (int32_t t = 0; t < k; ++t) {
            if (t >= out_counts[i]) {
                out[t] = -1;
                if (out_slots) out_slots[i * k + t] = -1;
                continue;
            }
            const double u =
                (double)(splitmix64(state) >> 11) * (1.0 / 9007199254740992.0)
                * total;               // 53-bit uniform in [0, total)
            const int64_t p =
                std::upper_bound(cdf.begin(), cdf.begin() + pool, u) -
                cdf.begin();
            const int64_t slot = row_start + std::min<int64_t>(p, pool - 1);
            out[t] = indices[slot];
            if (out_slots) out_slots[i * k + t] = slot;
        }
    }
}

}  // namespace

extern "C" {

// ABI version marker. The ctypes loader REQUIRES this symbol: the
// qt_sample_layer* signatures changed in v2 (appended out_slots), and
// symbol-name lookup alone cannot detect a stale prebuilt .so with the
// old signatures — calling one would silently return garbage slots.
void qt_abi_v2(void) {}

// Weighted (attention) draw: k picks with replacement ~ edge weight per
// seed, pool truncated at row_cap. out_nbrs [num_seeds * k] (-1 fill),
// out_counts [num_seeds] = min(deg, k), or 0 for zero-mass rows
// (nbrs all -1) — matching ops/weighted.py.
void qt_sample_layer_weighted(const int64_t *indptr, const int32_t *indices,
                              const float *weights, const int32_t *seeds,
                              int64_t num_seeds, int32_t k, int32_t row_cap,
                              uint64_t seed, int32_t *out_nbrs,
                              int32_t *out_counts, int64_t *out_slots,
                              int32_t num_threads) {
    if (num_seeds == 0) return;
    if (row_cap < 1) row_cap = 1;
    int32_t nt = num_threads > 0
                     ? num_threads
                     : (int32_t)std::thread::hardware_concurrency();
    nt = std::max(1, std::min<int32_t>(nt, (int32_t)num_seeds));
    if (nt == 1) {
        sample_range_weighted(indptr, indices, weights, seeds, 0, num_seeds,
                              k, row_cap, seed, out_nbrs, out_counts,
                              out_slots);
        return;
    }
    std::vector<std::thread> threads;
    const int64_t chunk = (num_seeds + nt - 1) / nt;
    for (int32_t t = 0; t < nt; ++t) {
        const int64_t lo = t * chunk;
        const int64_t hi = std::min(num_seeds, lo + chunk);
        if (lo >= hi) break;
        threads.emplace_back(sample_range_weighted, indptr, indices, weights,
                             seeds, lo, hi, k, row_cap, seed, out_nbrs,
                             out_counts, out_slots);
    }
    for (auto &th : threads) th.join();
}

// Sample up to k neighbors (uniform, without replacement) per seed.
// out_nbrs: [num_seeds * k] (-1 fill), out_counts: [num_seeds].
// out_slots (nullable): each pick's flat CSR slot, [num_seeds * k].
void qt_sample_layer(const int64_t *indptr, const int32_t *indices,
                     const int32_t *seeds, int64_t num_seeds, int32_t k,
                     uint64_t seed, int32_t *out_nbrs, int32_t *out_counts,
                     int64_t *out_slots, int32_t num_threads) {
    if (num_seeds == 0) return;
    int32_t nt = num_threads > 0
                     ? num_threads
                     : (int32_t)std::thread::hardware_concurrency();
    nt = std::max(1, std::min<int32_t>(nt, (int32_t)num_seeds));
    if (nt == 1) {
        sample_range(indptr, indices, seeds, 0, num_seeds, k, seed, out_nbrs,
                     out_counts, out_slots);
        return;
    }
    std::vector<std::thread> threads;
    const int64_t chunk = (num_seeds + nt - 1) / nt;
    for (int32_t t = 0; t < nt; ++t) {
        const int64_t lo = t * chunk;
        const int64_t hi = std::min(num_seeds, lo + chunk);
        if (lo >= hi) break;
        threads.emplace_back(sample_range, indptr, indices, seeds, lo, hi, k,
                             seed, out_nbrs, out_counts, out_slots);
    }
    for (auto &th : threads) th.join();
}

// Full-row degree lookup (== quiver::degree, quiver.cpu.hpp).
void qt_degree(const int64_t *indptr, const int32_t *seeds, int64_t num_seeds,
               int32_t *out_deg) {
    for (int64_t i = 0; i < num_seeds; ++i) {
        const int32_t v = seeds[i];
        out_deg[i] =
            v < 0 ? 0 : static_cast<int32_t>(indptr[v + 1] - indptr[v]);
    }
}

}  // extern "C"

extern "C" {

// First-occurrence reindex of one sampled hop — the host-side counterpart
// of the device layer compaction (reference CPU path: CPUQuiver's
// unordered_map reindex, srcs/cpp/src/quiver/quiver.cpp:11-119). Open
// addressing instead of std::unordered_map: one flat probe array, no
// per-node allocations.
//
// seeds [s] (-1 fill allowed), nbrs [s*k] (-1 fill).
// out_n_id [s + s*k]: unique ids, first-occurrence order (valid seeds
// first, packed), -1 fill. out_row/out_col [s*k]: local-id COO (-1 fill).
// Returns the number of valid unique ids.
int64_t qt_reindex(const int32_t *seeds, int64_t s, const int32_t *nbrs,
                   int32_t k, int32_t *out_n_id, int32_t *out_row,
                   int32_t *out_col) {
    const int64_t cap = s + s * (int64_t)k;
    uint64_t table_size = 16;
    while (table_size < (uint64_t)(2 * cap)) table_size <<= 1;
    std::vector<int32_t> keys(table_size, -1);
    std::vector<int32_t> vals(table_size, -1);
    const uint64_t mask = table_size - 1;

    int64_t count = 0;
    auto lookup_or_insert = [&](int32_t id) -> int32_t {
        uint64_t h = (uint64_t)(uint32_t)id * 0x9E3779B97F4A7C15ULL;
        uint64_t slot = (h >> 17) & mask;
        for (;;) {
            if (keys[slot] == id) return vals[slot];
            if (keys[slot] == -1) {
                keys[slot] = id;
                vals[slot] = (int32_t)count;
                out_n_id[count++] = id;
                return vals[slot];
            }
            slot = (slot + 1) & mask;
        }
    };

    std::vector<int32_t> seed_local(s);
    for (int64_t i = 0; i < s; ++i)
        seed_local[i] = seeds[i] < 0 ? -1 : lookup_or_insert(seeds[i]);
    for (int64_t i = 0; i < s; ++i) {
        for (int32_t t = 0; t < k; ++t) {
            const int64_t e = i * k + t;
            const int32_t nb = nbrs[e];
            if (nb < 0 || seed_local[i] < 0) {
                out_row[e] = -1;
                out_col[e] = -1;
            } else {
                out_row[e] = seed_local[i];
                out_col[e] = lookup_or_insert(nb);
            }
        }
    }
    std::fill(out_n_id + count, out_n_id + cap, -1);
    return count;
}

}  // extern "C"
