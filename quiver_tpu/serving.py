"""Online inference serving: request-coalescing micro-batch server.

The paper's split — sampling is *latency-critical*, feature collection
is *bandwidth-critical* — was optimized by the training-side PRs for
throughput. This module is the latency side's consumer: a point-query
server for GNN inference (recsys/fraud-style "embed/classify THIS
user now"), where hardware-accelerated sampling only pays off when many
small requests share one fixed-shape device dispatch.

Three layers, smallest first:

**``build_serve_step``** — one jitted, fixed-shape sample -> gather ->
forward program per fanout config: ``step(params, key, feat, forder,
indptr, indices, seeds)`` with ``seeds`` a ``[batch_cap]`` int32 block
(distinct valid ids first, ``-1`` fill at the tail — the training
builders' batch contract) returning ``(next_key, logits[batch_cap,
out_dim])``. The PRNG key is threaded THROUGH the program and its
buffer is donated, so per-dispatch RNG costs zero host work and zero
extra allocations; sampling reuses ``ops.sample_multihop``, the gather
reuses ``masked_feature_gather``/``dedup_feature_gather`` (quantized
stores compose — pass ``quant.quantize(feat, "int8")``), and the
forward is the in-tree flax model applied with ``train=False``.
``collect_metrics=True`` adds the ``metrics.NUM_COUNTERS`` device
counter vector as a third output (zero host syncs — pinned by
``tests/_traffic.host_sync_eqns``).

**``ServeEngine``** — owns the model params, the feature tier, the
topology and a BOUNDED set of pre-compiled fanout variants
(``sizes_variants``, full quality first, cheaper degradation targets
after). Every variant shares the ``[batch_cap]`` seed shape, so the
executable cache holds exactly ``len(sizes_variants)`` serve programs
for the life of the server (``scripts/check_leak.py`` phase 6 pins
flatness across mixed-variant traffic). ``warmup()`` compiles them all
up front — overload is precisely when a compile stall is least
affordable. A ``Feature`` store plugs in directly: its fused tiered
lookup (hot HBM rows + cold host rows, ``-1``-mask semantics,
``dedup_cold`` compaction) runs INSIDE the serve program.

**``MicroBatchServer``** — the async request path. ``submit(node_id)``
admits one request into a bounded queue and returns a
``concurrent.futures.Future``; a coalescer thread drains the queue
into ``[batch_cap]`` batches (duplicate node ids coalesced into the
SAME batch share one seed slot — the dedup convention applied at the
request layer; batches already dispatched are not revisited), a max-wait
deadline bounds how long a lone request can sit waiting for company,
and a ``pipeline.Pipeline`` executes batches so batch i+1 coalesces
while batch i runs. Results scatter back to each request's future.
Latency SLOs are first-class: per-REQUEST admission->result latency
lands in ``metrics.StepStats`` (``record_request``) and in a
``metrics.SloBudget`` (target p99 + availability, multi-window
error-budget burn rates), and overload degrades gracefully in two
stages — when queue depth crosses its threshold or the SLO budget
burns unsustainably (``SloBudget.should_shed``: short-window burn
above ``shed_burn_rate`` AND long-window burn above 1.0 — replacing
the raw recent-p99 trigger with a signal that also counts failures and
rejections) the server *sheds quality* (dispatches a smaller
pre-compiled fanout variant); when the admission queue is full it
*sheds load* (``submit`` raises :class:`OverloadError` immediately
instead of queueing unbounded work). ``snapshot()`` is one
JSONL-ready record (kind ``serving``, with an ``slo`` block when a
budget is configured).

Tenancy (qt-capacity) is an OPTIONAL fourth layer over the same
machinery: a ``{name: TenantClass}`` registry (see
``default_tenant_classes`` — interactive / batch / best_effort) makes
``submit(tenant=)`` file every request under an SLO class, and shed
order becomes POLICY instead of arrival luck. Load shed consumes
best-effort first (weighted admission shares under pressure, plus a
full queue displaces the newest lowest-priority queued request to
admit a higher-priority one — never the reverse); quality shed
consumes best-effort first too (under a shed episode batches coalesce
class-pure and each class ignores ``shed_grace`` ladder steps, so
interactive degrades last). Per-tenant accounting — request
histograms, burn/shed/reject counts, an optional per-class
``SloBudget`` — lands as the ``tenant`` JSONL kind
(``emit_tenants``). Tenancy is host-side queue discipline +
accounting only: it never touches the seed block or the compiled
programs, so logits are bit-identical with accounting on or off
(pinned in tests/test_traffic.py) and the executable cache stays flat
(``scripts/check_leak.py`` phase 16).

With ``quiver_tpu.tracing`` enabled every request leaves a span
timeline: per-request ``serve.admission_wait`` / ``serve.coalesce_wait``
/ ``serve.request`` spans (each stamped with its own ``trace_id`` AND
the ``batch`` id of the coalesced batch that carried it) and per-batch
``serve.batch_coalesce`` / ``serve.dispatch`` / ``serve.scatter`` spans
(stamped with the fanout variant) — "where did this request's 100 ms
go?" becomes one Perfetto click-through. Tracing is host-side only:
the jitted serve program is bit-identical with tracing on or off
(pinned in tests/test_serving.py).
"""

from __future__ import annotations

import logging
import queue
import threading
import time
from typing import Callable, List, Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np

from . import faults, tracing
from .parallel.train import (_fused_knobs, _fused_multihop_x,
                             dedup_feature_gather, layers_to_adjs,
                             masked_feature_gather)
from .profiling import hot_path
# the typed request-failure vocabulary is shared with the RPC plane
# (quiver_tpu.rpc defines it so the jax-free client can import it):
# ServerClosed = "this replica will never answer; go elsewhere",
# DeadlineExceeded = "the budget is spent; retrying cannot help"
from .rpc import DeadlineExceeded, ServerClosed

_log = logging.getLogger("quiver_tpu.serving")


class OverloadError(RuntimeError):
    """Raised by ``MicroBatchServer.submit`` when the admission queue is
    full — the load-shedding half of overload handling: rejecting at
    admission is the only response that keeps the latency of the
    requests already admitted bounded. When raised from
    ``submit_many``, ``futures`` carries the futures of the requests
    that WERE admitted before the queue filled (they still run)."""

    futures: Sequence = ()


# -- tenancy: per-tenant SLO classes (qt-capacity) ---------------------------


#: the built-in tenant SLO classes, highest priority first; shed order
#: is the REVERSE of this tuple (best_effort absorbs load- and
#: quality-shed first, interactive last). Pinned against
#: docs/observability.md by scripts/lint.sh.
TENANT_CLASS_NAMES = ("interactive", "batch", "best_effort")


class TenantClass:
    """One tenant SLO class — the unit of multi-tenant accounting and
    shed policy in :class:`MicroBatchServer`.

    - ``priority``: admission displacement order. A full queue evicts
      the newest queued request of the lowest priority STRICTLY below
      the arriving request's, never the reverse — so interactive
      admission consumes best-effort queue slots under overload.
    - ``admission_weight``: the class's guaranteed share of the
      admission queue. Under pressure (queue past the shed threshold)
      a class already holding its weighted share is rejected at the
      door while under-share classes still admit — a best-effort flood
      cannot starve interactive admission.
    - ``shed_grace``: how many quality-shed ladder steps this class's
      batches ignore. Grace 0 (best_effort) degrades at the first shed
      step; a grace at least the ladder depth (interactive's default)
      degrades only under a fleet-planned floor — quality shed
      consumes best-effort first, interactive last.
    - ``slo_p99_ms`` (+ the ``slo_*`` shape knobs): arms a per-class
      ``metrics.SloBudget`` for burn accounting. The SERVER's
      aggregate budget still drives the shed trigger; the per-class
      budget is the accounting the ``tenant`` JSONL kind reports.
    """

    def __init__(self, name: str, priority: int,
                 admission_weight: float = 1.0, shed_grace: int = 0,
                 slo_p99_ms: Optional[float] = None,
                 slo_availability: float = 0.99,
                 slo_window_s: float = 300.0,
                 slo_short_window_s: float = 30.0):
        if not name:
            raise ValueError("tenant class needs a name")
        if not admission_weight > 0.0:
            raise ValueError(
                f"admission_weight must be > 0, got {admission_weight}")
        if shed_grace < 0:
            raise ValueError(f"shed_grace must be >= 0, got {shed_grace}")
        self.name = str(name)
        self.priority = int(priority)
        self.admission_weight = float(admission_weight)
        self.shed_grace = int(shed_grace)
        self.slo_p99_ms = (None if slo_p99_ms is None
                           else float(slo_p99_ms))
        self.slo_availability = float(slo_availability)
        self.slo_window_s = float(slo_window_s)
        self.slo_short_window_s = float(slo_short_window_s)

    def make_budget(self):
        """A fresh per-class ``metrics.SloBudget`` (None when this
        class declares no latency target)."""
        from .metrics import SloBudget
        if self.slo_p99_ms is None:
            return None
        return SloBudget(self.slo_p99_ms,
                         availability=self.slo_availability,
                         window_s=self.slo_window_s,
                         short_window_s=self.slo_short_window_s)


def default_tenant_classes(slo_p99_ms: Optional[float] = None) -> dict:
    """The standard three-class registry (``TENANT_CLASS_NAMES``):
    interactive (priority 2, 4x admission weight, never quality-shed
    before the ladder is exhausted, SLO target ``slo_p99_ms``), batch
    (priority 1, 2x weight, one step of grace, 4x the latency target),
    best_effort (priority 0, weight 1, no grace, no latency target —
    it absorbs the shed). Pass the dict to
    ``MicroBatchServer(tenants=...)``."""
    return {
        "interactive": TenantClass(
            "interactive", priority=2, admission_weight=4.0,
            shed_grace=8, slo_p99_ms=slo_p99_ms),
        "batch": TenantClass(
            "batch", priority=1, admission_weight=2.0, shed_grace=1,
            slo_p99_ms=(4.0 * slo_p99_ms if slo_p99_ms is not None
                        else None)),
        "best_effort": TenantClass(
            "best_effort", priority=0, admission_weight=1.0,
            shed_grace=0),
    }


class _TenantState:
    """Per-class accounting the server keeps under ``_counts_lock``
    (except ``budget``, which locks itself)."""

    __slots__ = ("cls", "budget", "hist", "counts", "queued", "share")

    def __init__(self, cls: TenantClass, share: int):
        from .metrics import _Histogram
        self.cls = cls
        self.budget = cls.make_budget()
        self.hist = _Histogram()
        self.queued = 0
        self.share = share
        self.counts = {"requests": 0, "completed": 0, "rejected": 0,
                       "displaced": 0, "deadline_expired": 0,
                       "failed": 0}


# -- the jitted serve step ---------------------------------------------------


def build_serve_step(model, sizes: Sequence[int], batch_cap: int,
                     method: str = "exact",
                     dedup_gather=None,
                     gather: Optional[Callable] = None,
                     collect_metrics: bool = False,
                     fused_hot_hop: bool = False,
                     fused_row_cap: int = 2048,
                     fused_rng: Optional[str] = None,
                     fused_interpret: Optional[bool] = None,
                     fused_hot_rows: Optional[int] = None):
    """Pre-compiled point-inference step for one fanout config.

    Returns ``step(params, key, feat, forder, indptr, indices, seeds)``
    -> ``(next_key, logits)`` (plus the device counter vector with
    ``collect_metrics=True``). ``seeds`` is ``[batch_cap]`` int32,
    distinct valid ids first, -1 fill at the tail (the coalescer
    produces exactly this). Rows of padded slots are garbage — callers
    index only the valid prefix. The ``key`` argument's buffer is
    DONATED: the program splits it internally and returns the successor,
    so the caller threads one key chain through with no per-dispatch
    host-side RNG work (pass a fresh key only at the start).

    ``feat``/``forder``/topology are arguments, not closures (nothing
    large bakes into the executable); ``feat`` may be a quantized store.
    ``dedup_gather`` (True or an int unique budget) swaps the frontier
    gather for ``dedup_feature_gather``; ``gather`` overrides the whole
    gather callable (``gather(feat, n_id, forder, collector=None)`` —
    the ``ServeEngine`` uses this to splice a ``Feature`` store's fused
    tiered lookup into the program). The returned step exposes
    ``.jitted_fns`` (for ``StepStats.watch_compiles``) and ``.raw``
    (the traceable body, for jaxpr pins like ``host_sync_eqns``).

    ``fused_hot_hop=True`` (any ``sizes`` ladder, ``method="exact"``)
    swaps the sample+gather pair for the fused Pallas walk
    (``ops.pallas.fused.fused_multihop``): every hop samples in-kernel
    (interior hops run the sampling-only kernel, the leaf hop also
    gathers the dequantized hot-tier rows), frontier ids never touch
    HBM. ``fused_hot_rows`` scopes the in-kernel gather to the hot tier;
    when a ``gather`` override is also given (the ``ServeEngine``'s
    tiered ``Feature`` splice, where ``feat`` is the ``(device_part,
    host)`` pytree and the kernel reads ``feat[0]``), the slots the
    kernel masked as cold are overlaid from the store's unchanged
    tiered lookup afterwards — the fused kernel handles the hot tier
    only. ``fused_row_cap``/``fused_rng``/``fused_interpret`` are the
    kernel's knobs (see ``parallel.train.build_train_step``)."""
    sizes = list(sizes)
    if gather is None and dedup_gather is not None:
        budget = None if dedup_gather is True else int(dedup_gather)
        gather = (lambda feat, n_id, forder, collector=None:
                  dedup_feature_gather(feat, n_id, forder, budget,
                                       collector=collector))
    fused = _fused_knobs(fused_hot_hop, fused_row_cap, fused_rng,
                         fused_interpret, sizes, method,
                         dedup_gather=dedup_gather)
    if fused is not None and gather is not None and fused_hot_rows is None:
        raise ValueError(
            "fused_hot_hop over a spliced tiered gather needs "
            "fused_hot_rows (the hot-tier row count) to route cold "
            "picks back through the tiered lookup")

    @hot_path
    def forward(params, key, feat, forder, indptr, indices, seeds,
                collector=None):
        key, sub = jax.random.split(key)
        if fused is not None:
            hot = feat[0] if gather is not None else feat
            x, layers = _fused_multihop_x(
                hot, forder, indptr, indices, seeds, sizes, sub,
                hot_rows=fused_hot_rows, collector=collector, **fused)
            if gather is not None:
                # cold fixup: the kernel zeroed every frontier slot
                # whose translated row falls outside the hot tier;
                # those slots — and ONLY those — come from the store's
                # unchanged tiered lookup (hot slots masked to -1 so
                # the store reads nothing for them). The FINAL layer's
                # n_id is the whole walk's frontier.
                n_id = layers[-1].n_id
                t = forder[jnp.clip(n_id, 0)] if forder is not None \
                    else jnp.clip(n_id, 0)
                is_cold = (n_id >= 0) & (t >= fused_hot_rows)
                x_cold = gather(feat, jnp.where(is_cold, n_id, -1),
                                forder, collector=collector)
                x = jnp.where(is_cold[:, None], x_cold, x)
        else:
            n_id, layers = sample_multihop_serving(
                indptr, indices, seeds, sizes, sub, method=method,
                collector=collector)
            x = (gather or masked_feature_gather)(feat, n_id, forder,
                                                  collector=collector)
        adjs = layers_to_adjs(layers, batch_cap, sizes)
        with jax.named_scope("qt_serve_forward"):
            logits = model.apply(params, x, adjs, train=False)
        return key, logits[:batch_cap]

    @hot_path
    def raw(params, key, feat, forder, indptr, indices, seeds):
        if not collect_metrics:
            return forward(params, key, feat, forder, indptr, indices,
                           seeds)
        from .metrics import Collector
        col = Collector()
        key, logits = forward(params, key, feat, forder, indptr,
                              indices, seeds, col)
        return key, logits, col.counters()

    # the key is the one buffer the step both consumes and reproduces —
    # donating it makes the chain alias in place across dispatches
    jitted = jax.jit(raw, donate_argnums=(1,))
    jitted.jitted_fns = (jitted,)
    jitted.raw = raw
    return jitted


def sample_multihop_serving(indptr, indices, seeds, sizes, key,
                            method="exact", collector=None):
    """The serve step's sampling stage — ``ops.sample_multihop`` under
    the coalescer's batch contract (distinct valid seeds first, -1 tail
    fill => ``seeds_dense``). Split out so jaxpr pins can trace the
    sampling half alone."""
    from .ops.sample_multihop import sample_multihop
    return sample_multihop(indptr, indices, seeds, sizes, key,
                           method=method, seeds_dense=True,
                           collector=collector)


# -- the engine: params + tiers + pre-compiled variants ----------------------


class ServeEngine:
    """Pre-compiled fanout-variant set over one model + feature tier.

    ``sizes_variants`` is the BOUNDED degradation ladder: index 0 is
    full quality, later entries are the cheaper fanouts the server
    sheds to under pressure (all must have the same hop count — the
    model's layer count). One executable per variant, all sharing the
    ``[batch_cap]`` seed shape; nothing else ever compiles, so the
    executable cache stays flat under any traffic mix.

    ``feat`` is a plain array, a ``quant.QuantizedTensor``, or a
    ``quiver_tpu.Feature`` store — the store's fused tiered lookup
    (HBM hot rows + host cold rows, masked, ``dedup_cold``) is spliced
    into the serve program as its gather stage; stores with a disk/mmap
    tier are refused (their lookup is host-driven and cannot fuse).
    ``collect_metrics=True`` makes every ``run`` also emit the device
    counter vector (stashed on ``last_counters``; read it lazily).

    ``fused_hot_hop=True`` (exact method; any hop count — the ladder
    variants share one census bound) builds each variant on the fused
    Pallas walk: every hop samples in-kernel, the leaf hop gathers the
    hot-tier rows in the same kernel, and only cold frontier slots
    (when the store is tiered) take the split lookup. See
    ``build_serve_step``'s knob of the same name.

    ``run(seeds, variant=0)`` is NOT thread-safe (the donated key chain
    is serialized state) — the server funnels all dispatches through
    its single pipeline worker; direct callers must do the same.
    """

    def __init__(self, model, params, topo, feat,
                 sizes_variants: Sequence[Sequence[int]],
                 batch_cap: int,
                 forder=None,
                 method: str = "exact",
                 dedup_gather=None,
                 collect_metrics: bool = False,
                 fused_hot_hop: bool = False,
                 fused_row_cap: int = 2048,
                 seed: int = 0):
        if not sizes_variants:
            raise ValueError("need at least one fanout variant")
        hops = {len(s) for s in sizes_variants}
        if len(hops) != 1:
            raise ValueError(
                f"all fanout variants must share the model's hop count, "
                f"got lengths {sorted(hops)}")
        self.model = model
        self.params = params
        self.variants: List[List[int]] = [list(s) for s in sizes_variants]
        self.batch_cap = int(batch_cap)
        self.method = method
        self.collect_metrics = bool(collect_metrics)
        self.last_counters = None
        indptr, indices = (topo.indptr, topo.indices) \
            if hasattr(topo, "indptr") else topo
        self._indptr = jnp.asarray(indptr, jnp.int32)
        self._indices = jnp.asarray(indices, jnp.int32)
        gather = None
        self._store = None
        if hasattr(feat, "lookup_tiered"):        # a Feature store
            self._store = feat
            feat, forder, gather = _feature_gather(feat)
        elif isinstance(feat, np.ndarray):
            feat = jnp.asarray(feat)
        self._feat = feat
        self._forder = None if forder is None else \
            jnp.asarray(forder, jnp.int32)
        fused_kw = {}
        if fused_hot_hop:
            hot_rows = None
            if gather is not None:
                # tiered store: the kernel reads the (device_part, host)
                # pytree's hot part; cold picks route back through the
                # store's own lookup (the serve step's cold fixup)
                from .ops import quant
                hot_rows = quant.tier_rows(self._feat[0])
            fused_kw = dict(fused_hot_hop=True,
                            fused_row_cap=fused_row_cap,
                            fused_hot_rows=hot_rows)
        self._steps = [
            build_serve_step(model, sizes, self.batch_cap, method=method,
                             dedup_gather=dedup_gather, gather=gather,
                             collect_metrics=self.collect_metrics,
                             **fused_kw)
            for sizes in self.variants]
        self._key = jax.random.key(seed)

    @property
    def jitted_fns(self):
        """Every jitted serve program (one per variant) — feed to
        ``StepStats.watch_compiles`` so a mid-traffic recompile is a
        reported incident, not silent latency."""
        return tuple(f for s in self._steps for f in s.jitted_fns)

    def pad_seeds(self, node_ids) -> np.ndarray:
        """Host-side batch assembly: distinct valid ids first, -1 fill
        to ``[batch_cap]`` (the serve step's seed contract)."""
        ids = np.asarray(node_ids, np.int32).reshape(-1)
        if ids.shape[0] > self.batch_cap:
            raise ValueError(
                f"{ids.shape[0]} seeds exceed batch_cap={self.batch_cap}")
        out = np.full((self.batch_cap,), -1, np.int32)
        out[:ids.shape[0]] = ids
        return out

    def run(self, seeds, variant: int = 0):
        """Dispatch one ``[batch_cap]`` seed block through the given
        pre-compiled variant. Returns the ``[batch_cap, out_dim]``
        logits device array (no host sync — callers ``device_get`` when
        they scatter). ``seeds`` shorter than ``batch_cap`` are padded
        here; with ``collect_metrics`` the counter vector lands on
        ``last_counters``."""
        seeds = np.asarray(seeds, np.int32)
        if seeds.shape[0] != self.batch_cap:
            seeds = self.pad_seeds(seeds)
        out = self._steps[variant](
            self.params, self._key, self._feat, self._forder,
            self._indptr, self._indices, jnp.asarray(seeds))
        if self.collect_metrics:
            self._key, logits, self.last_counters = out
        else:
            self._key, logits = out
        return logits

    def warmup(self):
        """Compile every variant now (one dummy dispatch each) so the
        first real request — and the first SHED batch, which arrives
        exactly when the server is drowning — never eats a compile."""
        for v in range(len(self.variants)):
            jax.block_until_ready(self.run(
                np.zeros((self.batch_cap,), np.int32), v))
        return self

    def refresh_feature(self) -> "ServeEngine":
        """Re-splice the underlying ``Feature`` store's tier arrays
        into this engine after an online mutation
        (``Feature.rotate_hot_set``): the engine captured
        ``device_part``/``host_part``/``feature_order`` at
        construction, so a rotation the store applied would otherwise
        serve from the STALE pre-rotation arrays. The gather closure
        itself stays valid (it reads the tiers from program arguments),
        and the refreshed arrays must keep their shapes and dtypes —
        verified here, so a refresh can never recompile (the
        executable-cache flatness ``check_leak`` phase 13 pins)."""
        if self._store is None:
            raise ValueError(
                "refresh_feature needs an engine built over a Feature "
                "store (this one was built over a plain array)")
        feat, forder, _ = _feature_gather(self._store)

        def sig(t):
            return [(tuple(l.shape), str(l.dtype))
                    for l in jax.tree_util.tree_leaves(t)]

        if sig(feat) != sig(self._feat):
            raise ValueError(
                "refreshed feature tiers changed shape or dtype — "
                "refusing (the serve programs would recompile)")
        self._feat = feat
        self._forder = None if forder is None else \
            jnp.asarray(forder, jnp.int32)
        return self


def _feature_gather(feature):
    """Splice a ``Feature`` store's fused tiered lookup into the serve
    program: returns ``(feat_args, forder, gather)`` where ``feat_args``
    is the ``(device_part, host_tier)`` pytree the step passes through
    and ``gather`` runs the store's own traceable lookup body (masked,
    dedup_cold, quantized tiers — all its conventions) on it."""
    from .ops import quant
    if feature.mmap_array is not None:
        raise ValueError(
            "ServeEngine cannot fuse a disk/mmap-tier Feature store "
            "(its cold reads are host-driven); serve from a store whose "
            "tiers are HBM/host arrays")
    host = feature._host_offload
    if host is None and feature.host_part is not None:
        # numpy cold tier: commit once so the lookup fuses — the serve
        # path cannot afford a per-batch host round trip. Commit to
        # PINNED HOST memory (the store's own offload placement), not
        # device HBM: the cold tier is cold precisely because it does
        # not fit there. Loud jnp fallback only where host-offload is
        # unusable (CPU: host and device memory are the same arena).
        from .utils.placement import pinned_put
        devs = jax.devices()
        dev = devs[feature.rank if feature.rank < len(devs) else 0]
        leaves, tree = jax.tree_util.tree_flatten(feature.host_part)
        got = pinned_put(leaves, dev, True, "the serving cold tier",
                         mesh=feature.mesh)
        if got is not None:
            host = jax.tree_util.tree_unflatten(tree, got)
        else:
            host = quant.tree_map_tier(jnp.asarray, feature.host_part)
    if host is None:
        # pure-HBM store: the default masked gather over the cache part
        # IS the store's lookup (same translate + clip + mask semantics)
        return feature.device_part, feature.feature_order, None
    raw = feature._lookup_tiered_raw

    def gather(feat_args, n_id, forder, collector=None):
        dev, host_t = feat_args
        if collector is None:
            return raw(dev, host_t, n_id, forder, True)
        rows, vec = raw(dev, host_t, n_id, forder, True, True)
        collector.absorb(vec)
        return rows
    return (feature.device_part, host), feature.feature_order, gather


# -- sharded serving: one partitioned store under the whole fleet ------------


def build_sharded_serve_step(model, sizes: Sequence[int], batch_cap: int,
                             mesh, axis: str, rows_per_host: int,
                             method: str = "exact",
                             exchange_cap=None,
                             home: Optional[int] = None,
                             collect_metrics: bool = False,
                             fused_hot_hop: bool = False,
                             fused_row_cap: int = 2048,
                             fused_rng: Optional[str] = None,
                             fused_interpret: Optional[bool] = None):
    """The serve step over a ``DistFeature``-partitioned store: ONE
    jitted ``shard_map`` program per fanout config whose gather stage is
    the PR 4 compact deduplicated exchange (``comm.dist_lookup_local``)
    instead of a resident-array read.

    Returns ``step(params, key, spmd_feat, g2h, g2l, indptr, indices,
    seeds)`` -> ``(next_key, logits[batch_cap, out_dim])`` (plus the
    GLOBAL ``[metrics.NUM_COUNTERS]`` vector with ``collect_metrics``,
    ``pmerge_counters``-folded over the mesh axis on device).
    ``spmd_feat`` is the ``P(axis)``-sharded ``[H*rows_per_host, dim]``
    store (``DistFeature._spmd_feat``); everything else — topology,
    placement maps, the ``[batch_cap]`` seed block — is replicated, and
    sampling runs REPLICATED (no per-shard key fold), so the frontier,
    the adjacency structure and therefore the logits are bit-identical
    to the single-store ``build_serve_step`` over the same unpartitioned
    array (pinned in tests/test_serving.py): only WHERE the rows live
    changes, never which rows are read.

    ``exchange_cap`` (``True | int | None``): the compact [H, cap]
    request block; overflow falls back to the dense [H, F] exchange via
    the shard-uniform ``lax.pmax``'d ``lax.cond`` inside
    ``dist_lookup_local`` — row-identical either way, and the whole
    program still performs zero host syncs (qt-verify's
    ``no_host_sync`` / ``collective_divergence`` rules cover the traced
    body; per-variant ``executable_census`` bounds the program count).
    ``True`` sizes the cap from this variant's frontier capacity.

    ``home`` is THIS replica's partition (the one whose rows its hot
    tier holds). With ``collect_metrics``, every valid frontier row is
    classified once (on shard 0 only, so the device-side fold doesn't
    multiply it by the shard count): owned by ``home`` ->
    ``locality_hit_rows``, owned elsewhere -> ``locality_miss_rows`` —
    the router-as-cache-policy payoff counters (miss rows are exactly
    the rows the exchange must ship in from other partitions).

    ``fused_hot_hop=True`` (exact method) swaps the replicated sampling
    stage for the gather-free fused Pallas walk
    (``ops.pallas.fused.fused_sample_multihop``): every hop's degrees
    and CSR windows resolve in-kernel, so the sampling half contributes
    zero ``gather_index_bytes`` — the hot-tier leg of the sharded step.
    The feature rows still arrive through the unchanged partitioned
    exchange (``dist_lookup_local``); picks come from the kernel PRNG
    stream, so logits are bit-comparable with a fused single-store
    ``build_serve_step`` over the same rows, not with the split sharded
    step."""
    from .comm import default_exchange_cap, dist_lookup_local
    from ._compat import shard_map
    from jax.sharding import PartitionSpec as P

    sizes = list(sizes)
    fused = _fused_knobs(fused_hot_hop, fused_row_cap, fused_rng,
                         fused_interpret, sizes, method)
    h_count = mesh.shape[axis]
    if exchange_cap is True:
        from .pyg.sage_sampler import layer_shapes
        frontier = layer_shapes(batch_cap, sizes)[-1].n_id_cap
        exchange_cap = default_exchange_cap(frontier, h_count)
    elif exchange_cap is not None:
        exchange_cap = int(exchange_cap)

    @hot_path
    def per_shard(params, key, feat, g2h, g2l, indptr, indices, seeds):
        from .metrics import (LOCALITY_HIT_ROWS, LOCALITY_MISS_ROWS,
                              Collector, pmerge_counters)
        col = Collector() if collect_metrics else None
        # rep_col: counters of the REPLICATED compute (sampling,
        # locality classification) — identical on every shard, so they
        # fold in from shard 0 only; the exchange counters stay
        # per-shard in ``col`` (each shard really runs an exchange) and
        # psum to the true mesh-wide totals
        rep_col = Collector() if collect_metrics else None
        key, sub = jax.random.split(key)
        if fused is not None:
            from .ops.pallas.fused import (fused_sample_multihop,
                                           pad_indices)
            n_id, layers = fused_sample_multihop(
                indptr, pad_indices(indices, fused["row_cap"]), seeds,
                sizes, sub, **fused)
            if rep_col is not None:
                from .metrics import FRONTIER_CAP, FRONTIER_VALID
                rep_col.add(FRONTIER_VALID, jnp.sum(n_id >= 0))
                rep_col.add(FRONTIER_CAP, int(n_id.shape[0]))
        else:
            n_id, layers = sample_multihop_serving(
                indptr, indices, seeds, sizes, sub, method=method,
                collector=rep_col)
        x = dist_lookup_local(n_id, g2h, g2l, feat, axis, h_count,
                              rows_per_host, exchange_cap=exchange_cap,
                              collector=col)
        adjs = layers_to_adjs(layers, batch_cap, sizes)
        with jax.named_scope("qt_serve_forward"):
            logits = model.apply(params, x, adjs, train=False)
        if not collect_metrics:
            return key, logits[:batch_cap]
        if home is not None:
            valid = n_id >= 0
            owner = g2h[jnp.clip(n_id, 0)]
            rep_col.add(LOCALITY_HIT_ROWS,
                        jnp.sum(valid & (owner == home)))
            rep_col.add(LOCALITY_MISS_ROWS,
                        jnp.sum(valid & (owner != home)))
        first = jax.lax.axis_index(axis) == 0
        col.absorb(jnp.where(first, rep_col.counters(), 0))
        return key, logits[:batch_cap], pmerge_counters(col.counters(),
                                                        axis)

    outs = (P(), P(), P()) if collect_metrics else (P(), P())
    raw = shard_map(per_shard, mesh=mesh,
                    in_specs=(P(), P(), P(axis), P(), P(), P(), P(), P()),
                    out_specs=outs, check_vma=False)
    jitted = jax.jit(raw, donate_argnums=(1,))
    jitted.jitted_fns = (jitted,)
    jitted.raw = raw
    return jitted


class ShardedServeEngine:
    """A ``ServeEngine`` whose feature tier is ONE partition-sharded
    store shared by the whole replica fleet (``DistFeature``) instead of
    a per-replica copy — the qt-shard path across the single-host
    memory wall: each replica holds ``~1/P`` of the rows, and frontier
    rows owned elsewhere arrive through the compact deduplicated
    exchange INSIDE the jitted serve program.

    ``dist`` must be a ``DistFeature`` built with ``from_partition``
    (the SPMD mode); ``home`` names this replica's own partition
    (default ``dist.info.host``) — it scopes the locality hit/miss
    counters and rides the ``serving`` snapshot so the fleet plane
    (``qt_top``, the locality router) can see per-replica ownership.
    The exchange knob comes from ``dist.exchange_cap``; counters honor
    ``dist.collect_metrics`` semantics but are always folded to the
    GLOBAL vector on device (``merge_counters`` has no per-shard mode
    here — a serving replica wants one picture, not H rows).

    Same dispatch contract as ``ServeEngine`` (``run`` is NOT
    thread-safe; the ``MicroBatchServer`` funnels dispatches through
    its single pipeline worker), same bounded pre-compiled fanout
    ladder, and the logits are bit-identical to a single-store
    ``ServeEngine`` over the unpartitioned array (with
    ``fused_hot_hop=True`` on both — the fused sampling leg of
    ``build_sharded_serve_step`` — the match is against the fused
    single-store engine's kernel-PRNG stream)."""

    def __init__(self, model, params, topo, dist,
                 sizes_variants: Sequence[Sequence[int]],
                 batch_cap: int,
                 method: str = "exact",
                 home: Optional[int] = None,
                 collect_metrics: bool = False,
                 fused_hot_hop: bool = False,
                 fused_row_cap: int = 2048,
                 seed: int = 0):
        if not sizes_variants:
            raise ValueError("need at least one fanout variant")
        hops = {len(s) for s in sizes_variants}
        if len(hops) != 1:
            raise ValueError(
                f"all fanout variants must share the model's hop count, "
                f"got lengths {sorted(hops)}")
        if getattr(dist, "_spmd_feat", None) is None:
            raise ValueError(
                "ShardedServeEngine needs a DistFeature built with "
                "from_partition (the SPMD mode)")
        if getattr(dist, "_rep_args", None) is not None:
            raise ValueError(
                "ShardedServeEngine does not support replicated-tail "
                "stores yet; partition without replicate=")
        self.model = model
        self.params = params
        self.dist = dist
        self.variants: List[List[int]] = [list(s) for s in sizes_variants]
        self.batch_cap = int(batch_cap)
        self.method = method
        self.home = int(dist.info.host if home is None else home)
        self.partitions = int(dist.info.hosts)
        self.collect_metrics = bool(collect_metrics)
        self.last_counters = None
        indptr, indices = (topo.indptr, topo.indices) \
            if hasattr(topo, "indptr") else topo
        self._indptr = jnp.asarray(indptr, jnp.int32)
        self._indices = jnp.asarray(indices, jnp.int32)
        self._g2h = dist.info.global2host.astype(jnp.int32)
        self._g2l = dist.info.global2local
        self._steps = [
            build_sharded_serve_step(
                model, sizes, self.batch_cap, dist.comm.mesh,
                dist.comm.axis, dist._rows_per_host, method=method,
                exchange_cap=dist.exchange_cap, home=self.home,
                collect_metrics=self.collect_metrics,
                fused_hot_hop=fused_hot_hop,
                fused_row_cap=fused_row_cap)
            for sizes in self.variants]
        self._key = jax.random.key(seed)

    @property
    def jitted_fns(self):
        return tuple(f for s in self._steps for f in s.jitted_fns)

    pad_seeds = ServeEngine.pad_seeds

    def run(self, seeds, variant: int = 0):
        """Dispatch one ``[batch_cap]`` seed block through the given
        pre-compiled sharded variant (see ``ServeEngine.run``)."""
        seeds = np.asarray(seeds, np.int32)
        if seeds.shape[0] != self.batch_cap:
            seeds = self.pad_seeds(seeds)
        out = self._steps[variant](
            self.params, self._key, self.dist._spmd_feat, self._g2h,
            self._g2l, self._indptr, self._indices, jnp.asarray(seeds))
        if self.collect_metrics:
            self._key, logits, self.last_counters = out
        else:
            self._key, logits = out
        return logits

    def warmup(self):
        # 4 dispatches per variant, not 1: the donated key buffer's
        # placement settles over the first few executions (uncommitted
        # single-device -> mesh-replicated -> steady), each a distinct
        # jit signature — warming to the steady state keeps serving
        # recompile-free (pinned by scripts/check_leak.py phase 14)
        for v in range(len(self.variants)):
            for _ in range(4):
                jax.block_until_ready(self.run(
                    np.zeros((self.batch_cap,), np.int32), v))
        return self


# -- the server: admission, coalescing, shedding, scatter --------------------


class ServeConfig:
    """Knobs for :class:`MicroBatchServer` (all latency budgets in ms).

    - ``max_wait_ms``: coalescing deadline — how long the FIRST request
      of a batch may wait for company before the batch dispatches
      anyway. The lone-request worst case adds exactly this much.
    - ``queue_depth``: admission bound; a full queue sheds load
      (``submit`` raises :class:`OverloadError`).
    - ``slo_p99_ms``: per-request latency target. Setting it arms a
      ``metrics.SloBudget`` (target p99 at ``slo_availability`` over
      sliding windows); the server sheds QUALITY — dispatches escalate
      one step down the engine's fanout ladder — while the budget burns
      unsustainably (short-window burn rate above ``shed_burn_rate``
      AND long-window burn above 1.0), and recovers one step after
      ``calm_batches`` consecutive calm decisions (hysteresis,
      unchanged from the old raw-p99 trigger). Failed and
      admission-rejected requests count against the budget too — the
      raw p99 never saw them.
    - ``slo_availability`` / ``slo_window_s`` / ``slo_short_window_s``
      / ``shed_burn_rate``: the budget's shape — tolerated bad
      fraction is ``1 - slo_availability`` (default 0.99: a literal
      p99 target) over ``slo_window_s``, with the reactive burn rate
      measured over ``slo_short_window_s``.
    - ``shed_queue_frac``: queue fullness (0..1) that also triggers a
      quality-shed step — backlog is tomorrow's latency, so the server
      reacts before the SLO is already blown.
    - ``pipeline_depth``: in-flight batch bound (coalesce i+1 while i
      runs; more depth adds queueing latency, not throughput, past 2).
    """

    def __init__(self, max_wait_ms: float = 2.0, queue_depth: int = 256,
                 slo_p99_ms: Optional[float] = None,
                 slo_availability: float = 0.99,
                 slo_window_s: float = 300.0,
                 slo_short_window_s: float = 30.0,
                 shed_burn_rate: float = 1.0,
                 shed_queue_frac: float = 0.5,
                 calm_batches: int = 8,
                 pipeline_depth: int = 2):
        if queue_depth < 1:
            raise ValueError("queue_depth must be >= 1")
        if not 0.0 < shed_queue_frac <= 1.0:
            raise ValueError("shed_queue_frac must be in (0, 1]")
        self.max_wait_ms = float(max_wait_ms)
        self.queue_depth = int(queue_depth)
        self.slo_p99_ms = slo_p99_ms
        self.slo_availability = float(slo_availability)
        self.slo_window_s = float(slo_window_s)
        self.slo_short_window_s = float(slo_short_window_s)
        self.shed_burn_rate = float(shed_burn_rate)
        self.shed_queue_frac = float(shed_queue_frac)
        self.calm_batches = int(calm_batches)
        self.pipeline_depth = int(pipeline_depth)


def _fail_future(fut, exc) -> bool:
    """Claim-and-fail one request future, tolerating a future some
    OTHER path already resolved: ``submit``'s close-race handler and
    ``close()``'s queue drain can both reach the same queued request
    (the handler completes the future while the request still sits in
    the queue the drain is about to sweep) — stdlib
    ``set_running_or_notify_cancel`` RAISES on a finished future, so
    the loser of that race must treat it as "already handled", not
    crash ``close()``. Returns True when THIS call failed the
    future."""
    try:
        claimed = fut.set_running_or_notify_cancel()
    except RuntimeError:
        return False                 # already resolved elsewhere
    if claimed:
        fut.set_exception(exc)
    return claimed


class _Request:
    __slots__ = ("node_id", "future", "t_enq", "trace_id", "deadline",
                 "tenant")

    def __init__(self, node_id: int, future, t_enq: float,
                 trace_id=None, deadline: Optional[float] = None,
                 tenant: Optional[str] = None):
        self.node_id = node_id
        self.future = future
        self.t_enq = t_enq
        self.trace_id = trace_id
        self.deadline = deadline
        self.tenant = tenant


class MicroBatchServer:
    """Request-coalescing micro-batch front end over a ``ServeEngine``.

    ``submit(node_id)`` -> ``Future`` whose result is that node's
    ``[out_dim]`` numpy logits row (duplicate node ids landing in the
    same coalesced batch share one seed slot and one device read). Life cycle: ``start()`` spins
    the coalescer (done by the constructor unless ``start=False`` —
    tests use the paused form to stage bursts), ``close()`` rejects new
    work, fails queued requests loudly, and shuts the pipeline down
    (idempotent; also a context manager). ``snapshot()`` returns the
    JSONL-ready ``serving`` record; ``emit(sink)`` writes it.

    See :class:`ServeConfig` for the SLO/overload policy and the module
    docstring for the architecture."""

    def __init__(self, engine: ServeEngine,
                 config: Optional[ServeConfig] = None,
                 stats=None, start: bool = True, hub=None,
                 tenants: Optional[dict] = None):
        from .metrics import SloBudget, StepStats, register_report_section
        from .pipeline import Pipeline
        self.engine = engine
        self.config = config or ServeConfig()
        self.stats = stats if stats is not None else StepStats()
        self.stats.watch_compiles(*engine.jitted_fns)
        # hub: a telemetry.TelemetryHub fed per-BATCH series points
        # (fill, dispatch ms, shed level) plus the device counter
        # vectors when the engine collects them — the time-series the
        # batch_cap/max_wait advisor sizes from. Host-side appends on
        # the executor thread; the dispatch path is untouched.
        self.hub = hub
        self._report_name = f"serving@{id(self):x}"
        cfg = self.config
        # the SLO budget is the shed policy's latency signal (burn
        # rates, not raw p99 samples) AND the `slo` JSONL payload;
        # public — read it, or `server.slo.emit(sink)` it, any time
        self.slo: Optional[SloBudget] = None
        if cfg.slo_p99_ms is not None:
            self.slo = SloBudget(cfg.slo_p99_ms,
                                 availability=cfg.slo_availability,
                                 window_s=cfg.slo_window_s,
                                 short_window_s=cfg.slo_short_window_s,
                                 shed_burn_rate=cfg.shed_burn_rate)
        # tenancy (qt-capacity): OPTIONAL {name: TenantClass} registry.
        # None (the default) disables the whole plane; with a registry,
        # every request files under a class (None tenant -> the
        # lowest-priority class) and shed ORDER becomes policy — see
        # the module docstring. Tenancy is host-side accounting + queue
        # discipline only: it never changes the seed block or which
        # programs compile.
        self._tenants: Optional[dict] = None
        self._tenant_default: Optional[str] = None
        self._tenant_states: dict = {}
        # requests popped by the coalescer but deferred to a later
        # batch (class-pure coalescing under a shed episode);
        # coalescer-thread-only, swept by close()/the death watchdog
        self._held: list = []
        if tenants:
            reg = dict(tenants)
            for n, c in reg.items():
                if not isinstance(c, TenantClass):
                    raise TypeError(
                        f"tenants[{n!r}] must be a TenantClass")
                if n != c.name:
                    raise ValueError(
                        f"tenant registry key {n!r} names a class "
                        f"called {c.name!r}")
            self._tenants = reg
            self._tenant_default = min(
                reg, key=lambda n: (reg[n].priority, n))
            wsum = sum(c.admission_weight for c in reg.values())
            for n, c in reg.items():
                share = max(1, int(np.ceil(
                    cfg.queue_depth * c.admission_weight / wsum)))
                self._tenant_states[n] = _TenantState(c, share)
        self._q: "queue.Queue[_Request]" = queue.Queue(
            maxsize=self.config.queue_depth)
        self._pipe = Pipeline(depth=self.config.pipeline_depth,
                              name="quiver-serving-exec")
        self.stats.watch_pipeline(self._pipe)
        self._closed = False
        # broken = the coalescer thread died UNEXPECTEDLY (not close):
        # nothing will ever drain the queue again, so submissions must
        # fail fast with ServerClosed instead of hanging on admission
        self._broken = False
        self._lock = threading.Lock()
        self._thread: Optional[threading.Thread] = None
        # shedding state (coalescer-thread only, except the counters)
        self._shed_level = 0
        self._calm = 0
        # actuation surfaces (quiver_tpu.actuator): the EFFECTIVE
        # coalescing knobs, re-read by the coalescer per batch so a
        # swap lands on the next batch without a restart. The seed
        # shape stays [engine.batch_cap] whatever the fill cap, so no
        # knob swap can ever compile a new program.
        self._max_wait_s = cfg.max_wait_ms / 1e3
        self._fill_cap = engine.batch_cap
        self._shed_floor = 0
        self._counts = {
            "requests": 0, "rejected": 0, "completed": 0, "failed": 0,
            "deadline_expired": 0, "displaced": 0,
            "batches": 0, "coalesced": 0,
            "variant_batches": [0] * len(engine.variants),
        }
        self._counts_lock = threading.Lock()
        # register into the unified qt.metrics.report() LAST — a
        # constructor that raises above must not leak a permanently
        # broken section (close(), which unregisters, is unreachable
        # on a half-built server); unique name so parallel servers
        # coexist
        register_report_section(self._report_name, self.report)
        if start:
            self.start()

    # -- life cycle ---------------------------------------------------------
    def start(self) -> "MicroBatchServer":
        with self._lock:
            if self._closed or self._broken:
                raise ServerClosed("server is closed")
            if self._thread is None:
                t = threading.Thread(target=self._coalesce_guard,
                                     name="quiver-serving-coalescer",
                                     daemon=True)
                t.start()
                self._thread = t
        return self

    def close(self):
        """Reject new submissions, fail queued (never-dispatched)
        requests with ``RuntimeError``, drain the in-flight batches,
        stop the coalescer and the pipeline. Idempotent."""
        from .metrics import unregister_report_section
        unregister_report_section(self._report_name)
        with self._lock:
            if self._closed:
                return
            self._closed = True
            t = self._thread
            self._thread = None
        if t is not None and t is not threading.current_thread():
            t.join()
        # the coalescer is gone: anything still queued will never run
        # (held requests — popped but deferred by class-pure
        # coalescing — are safe to sweep here: the thread is joined)
        undispatched = list(self._held)
        self._held = []
        while True:
            try:
                undispatched.append(self._q.get_nowait())
            except queue.Empty:
                break
        self._fail_batch(undispatched)
        # coalesced batches still QUEUED in the pipeline are cancelled
        # by its close; their done-callbacks (armed at submit) fail the
        # request futures — the running batch drains normally first
        self._pipe.close()

    def __enter__(self) -> "MicroBatchServer":
        return self

    def __exit__(self, *exc):
        self.close()

    @property
    def closed(self) -> bool:
        return self._closed

    # -- admission ----------------------------------------------------------
    def _account_shed(self, tenant: Optional[str], key: str) -> None:
        """File one shed outcome (admission ``rejected``,
        ``displaced``, or ``deadline_expired``) into the aggregate
        counters, the aggregate SLO budget, and the owning tenant's
        accounting — one helper so load shed, displacement and
        deadline shed can never drift apart."""
        if self.slo is not None:
            # a shed request is an availability miss — the budget
            # must see it (the old raw-p99 trigger never did)
            self.slo.record(ok=False)
        st = self._tenant_states.get(tenant) if tenant else None
        with self._counts_lock:
            self._counts[key] += 1
            if st is not None:
                st.counts[key] += 1
        if st is not None and st.budget is not None:
            st.budget.record(ok=False)

    def _displace_for(self, priority: int):
        """Queue-discipline load shed: evict the NEWEST queued request
        of the lowest priority STRICTLY below ``priority`` to make
        room for a higher-priority admission (tenancy only). The
        victim's future fails with :class:`OverloadError` and its
        class absorbs the shed. Returns True when a slot was freed."""
        q = self._q
        with q.mutex:
            best_i, best_p = -1, priority
            for i in range(len(q.queue) - 1, -1, -1):
                p = self._tenants[q.queue[i].tenant].priority
                if p < best_p:
                    best_i, best_p = i, p
            if best_i < 0:
                return False
            victim = q.queue[best_i]
            del q.queue[best_i]
            q.not_full.notify()
        with self._counts_lock:
            self._tenant_states[victim.tenant].queued -= 1
        if _fail_future(victim.future, OverloadError(
                "displaced at admission by a higher-priority tenant")):
            self._account_shed(victim.tenant, "displaced")
        return True

    def submit(self, node_id: int, context=None,
               deadline: Optional[float] = None,
               tenant: Optional[str] = None):
        """Admit one point query; returns a ``Future`` resolving to the
        node's logits row (numpy ``[out_dim]``). Raises
        :class:`OverloadError` IMMEDIATELY when the admission queue is
        full — rejecting at the door is the overload policy's last
        stage (see :class:`ServeConfig`) — and
        :class:`~quiver_tpu.rpc.ServerClosed` when the server is
        closed OR its coalescer thread died (the thread-death watchdog:
        a request that nothing will ever drain must fail fast, never
        hang on the admission queue).

        ``deadline`` (absolute ``time.perf_counter()`` instant — the
        RPC front end converts its wire budget) arms per-request
        deadline shedding: a request whose deadline passes while it
        waits is failed with
        :class:`~quiver_tpu.rpc.DeadlineExceeded` at coalesce time,
        BEFORE it wastes a seed slot in a batch the client has already
        given up on.

        ``context`` is optional request metadata carrying a propagated
        trace context (``tracing.inject`` on the client side): when
        tracing is on, this request's spans record under the CLIENT's
        ``trace_id`` instead of a locally minted one, so the client's
        and this replica's exported traces correlate in one merged
        Perfetto view (``tracing.merge_chrome_traces``). A missing or
        mangled context falls back to a local id — never an error.

        ``tenant`` names the request's :class:`TenantClass` when the
        server was built with a registry (``tenants=``): the request
        files under that class's accounting and shed policy (a
        ``None`` tenant lands in the lowest-priority class; an
        unregistered name raises ``ValueError``). Without a registry
        the argument is accepted and ignored — RPC front ends thread
        it through unconditionally."""
        if self._closed or self._broken:
            raise ServerClosed("server is closed"
                               if self._closed else
                               "server is broken (coalescer died)")
        tname = None
        st = None
        if self._tenants is not None:
            tname = tenant if tenant is not None else \
                self._tenant_default
            st = self._tenant_states.get(tname)
            if st is None:
                raise ValueError(
                    f"unknown tenant class {tname!r} (registered: "
                    f"{sorted(self._tenants)})")
        from concurrent.futures import Future
        fut: Future = Future()
        tid = None
        if tracing.enabled():
            ctx = tracing.extract(context) if context is not None \
                else None
            tid = ctx.trace_id if ctx is not None \
                else tracing.new_trace_id()
        req = _Request(int(node_id), fut, time.perf_counter(), tid,
                       deadline, tname)
        cfg = self.config
        if st is not None:
            # weighted admission shares, enforced only under pressure
            # (queue past the shed threshold): a class already holding
            # its share of the queue is rejected at the door while
            # under-share classes still admit — load shed consumes the
            # flooding class first, and a calm queue never rejects
            shed_at = max(1, int(cfg.queue_depth * cfg.shed_queue_frac))
            if self._q.qsize() >= shed_at and st.queued >= st.share:
                self._account_shed(tname, "rejected")
                raise OverloadError(
                    f"admission queue pressed and tenant {tname!r} "
                    f"holds its share ({st.share}); request shed")
        try:
            self._q.put_nowait(req)
        except queue.Full:
            # tenancy: a full queue displaces the newest queued
            # request of a strictly lower priority before giving up —
            # interactive admission consumes best-effort slots, never
            # the reverse (one retry; a lost race with another
            # submitter degrades to an honest reject)
            admitted = False
            if st is not None and self._displace_for(st.cls.priority):
                try:
                    self._q.put_nowait(req)
                    admitted = True
                except queue.Full:
                    pass
            if not admitted:
                self._account_shed(tname, "rejected")
                raise OverloadError(
                    f"admission queue full ({cfg.queue_depth} "
                    "pending); request shed") from None
        if self._closed or self._broken:
            # close() (or the coalescer-death watchdog) raced us: its
            # drain may have run before our put landed, and no
            # coalescer will ever pop the request — reclaim it so the
            # future cannot strand (the claim is exclusive, so if the
            # drain got there first this is a no-op and the future is
            # already failed)
            _fail_future(req.future, ServerClosed("server is closed"))
            raise ServerClosed("server is closed")
        with self._counts_lock:
            self._counts["requests"] += 1
            if st is not None:
                st.counts["requests"] += 1
                st.queued += 1
        return fut

    def submit_many(self, node_ids, context=None,
                    deadline: Optional[float] = None,
                    tenant: Optional[str] = None) -> list:
        """``submit`` per id (one shared ``context`` — a multi-point
        client operation traces as ONE request id across its points).
        If admission overloads mid-list the raised
        :class:`OverloadError` carries the already-admitted futures on
        ``.futures`` — admitted work runs regardless, so its results
        must stay observable (and a retry must not resubmit them)."""
        futs: list = []
        for i in node_ids:
            try:
                futs.append(self.submit(i, context=context,
                                        deadline=deadline,
                                        tenant=tenant))
            except OverloadError as e:
                e.futures = futs
                raise
        return futs

    # -- actuation surfaces (qt-act) ----------------------------------------
    def set_max_wait_ms(self, ms: float) -> None:
        """Swap the effective coalescing deadline (the ``max_wait_ms``
        knob the hub's advisor sizes). Takes effect on the NEXT batch;
        no program input changes, so nothing recompiles."""
        ms = float(ms)
        if not ms > 0.0:
            raise ValueError(f"max_wait_ms must be > 0, got {ms}")
        self._max_wait_s = ms / 1e3

    def set_batch_fill_cap(self, cap: Optional[int]) -> None:
        """Swap the effective coalescing FILL cap (the ``batch_cap``
        knob's safe actuation form): batches stop coalescing at ``cap``
        distinct seeds but still dispatch at the engine's compiled
        ``[batch_cap]`` seed shape (-1 padded), so every value in
        ``[1, engine.batch_cap]`` reuses the census'd executables
        verbatim. ``None`` restores the engine cap. Growing past the
        compiled shape is impossible by construction — the actuator
        refuses such advice instead of recompiling."""
        if cap is None:
            self._fill_cap = self.engine.batch_cap
            return
        cap = int(cap)
        if not 1 <= cap <= self.engine.batch_cap:
            raise ValueError(
                f"batch fill cap must be in [1, "
                f"{self.engine.batch_cap}], got {cap}")
        self._fill_cap = cap

    def set_shed_floor(self, level: int) -> None:
        """Planned fleet-wide quality floor
        (``fleet.HealthRouter.plan_quality``): dispatches never run a
        variant ABOVE quality ``level`` while the floor is raised — the
        local hysteresis still escalates further under local pressure.
        0 restores full local autonomy."""
        level = int(level)
        top = len(self.engine.variants) - 1
        if not 0 <= level <= top:
            raise ValueError(
                f"shed floor must be in [0, {top}], got {level}")
        self._shed_floor = level

    def knobs(self) -> dict:
        """The effective actuation knobs (the ``before``/``after``
        readbacks the ``actuate`` JSONL records carry)."""
        return {"max_wait_ms": round(self._max_wait_s * 1e3, 6),
                "batch_fill_cap": self._fill_cap,
                "shed_floor": self._shed_floor}

    # -- coalescing ---------------------------------------------------------
    def _coalesce_guard(self):
        """The coalescer's thread-death watchdog: any exception
        escaping the loop (an injected ``serve.coalesce`` fault, a bug)
        marks the server BROKEN, fails every queued future with
        ``ServerClosed`` immediately — a dead coalescer means nothing
        will ever drain the queue, and a fast typed failure beats a
        silent hang — then re-raises so the death stays visible."""
        try:
            self._coalesce_loop()
        except BaseException as e:
            if self._closed:
                raise
            self._broken = True
            _log.error("serving coalescer died unexpectedly (%s: %s); "
                       "failing queued requests with ServerClosed",
                       type(e).__name__, e)
            undispatched = list(self._held)
            self._held = []
            while True:
                try:
                    undispatched.append(self._q.get_nowait())
                except queue.Empty:
                    break
            self._fail_batch(undispatched,
                             "coalescer thread died; server is broken",
                             exc_type=ServerClosed)
            raise

    def _shed_expired(self, req) -> bool:
        """Fail ``req`` with DeadlineExceeded if its deadline already
        passed — BEFORE it costs a batch seed slot. Returns True when
        the request was shed (or already claimed elsewhere)."""
        if req.deadline is None or time.perf_counter() <= req.deadline:
            return False
        if _fail_future(req.future, DeadlineExceeded(
                "deadline passed while queued (shed at coalesce — the "
                "client has already given up on this request)")):
            self._account_shed(req.tenant, "deadline_expired")
            if tracing.enabled() and req.trace_id is not None:
                # the request's TERMINAL span, error-stamped: a shed
                # request still completes its trace, so the tail
                # sampler can keep it (deadline_exceeded policy)
                now = time.perf_counter()
                tracing.record("serve.request", req.t_enq,
                               now - req.t_enq, req.trace_id,
                               {"node": req.node_id,
                                "error": "DeadlineExceeded"})
        return True

    def _note_popped(self, req) -> None:
        """Per-tenant queued-count bookkeeping for one admission-queue
        pop (weighted-share admission reads these counts)."""
        if self._tenants is not None:
            with self._counts_lock:
                self._tenant_states[req.tenant].queued -= 1

    def _pop_next(self, timeout: float):
        """Next request for the coalescer: deferred (held) requests
        first — oldest first, so class-pure deferral never starves a
        class — then the admission queue. Raises ``queue.Empty`` on
        timeout."""
        if self._held:
            return self._held.pop(0)
        req = self._q.get(timeout=timeout)
        self._note_popped(req)
        return req

    def _coalesce_loop(self):
        while not self._closed:
            faults.fire("serve.coalesce")
            # effective knobs re-read per batch: the actuator may swap
            # them mid-traffic (set_max_wait_ms / set_batch_fill_cap),
            # and a swap must land on the NEXT batch without a restart
            max_wait = self._max_wait_s
            cap = min(self._fill_cap, self.engine.batch_cap)
            try:
                first = self._pop_next(0.02)
            except queue.Empty:
                continue
            if self._shed_expired(first):
                continue
            # tenancy: under a shed episode batches coalesce
            # CLASS-PURE (the batch takes only the first request's
            # class; other classes defer to their own next batch), so
            # the per-class shed_grace variant applies per batch —
            # quality shed consumes best-effort first. Calm traffic
            # (shed level 0, no floor) coalesces mixed: every class
            # dispatches variant 0 there, so batch composition — and
            # the logits — are unchanged by tenancy. The first batch
            # of an episode (the one whose _select_variant call raises
            # the level) is still mixed: the discipline lags pressure
            # by exactly one batch.
            bcls = None
            if self._tenants is not None and (
                    self._shed_level > 0 or self._shed_floor > 0):
                bcls = self._tenants[first.tenant]
            # span plumbing: one enabled-check per batch when tracing is
            # off; when on, each request gets admission_wait (queue time
            # before the coalescer saw it) and coalesce_wait (time spent
            # waiting for batch company) spans carrying its trace_id +
            # the batch id — the request<->batch correlation the
            # Perfetto view pivots on
            traced = tracing.enabled()
            bid = tracing.new_trace_id() if traced else None
            t_first = time.perf_counter()
            pops = [(first, t_first)]
            if traced:
                tracing.record("serve.admission_wait", first.t_enq,
                               t_first - first.t_enq, first.trace_id,
                               {"batch": bid, "node": first.node_id})
            batch = [first]
            slots = {first.node_id: 0}
            if bcls is not None and self._held:
                # sweep already-deferred requests of THIS class into
                # the batch up front (one pass — the rest stay held)
                keep = []
                for r in self._held:
                    if (len(slots) < cap
                            and self._tenants[r.tenant] is bcls):
                        if self._shed_expired(r):
                            continue
                        batch.append(r)
                        slots.setdefault(r.node_id, len(slots))
                        if traced:
                            t_pop = time.perf_counter()
                            pops.append((r, t_pop))
                            tracing.record(
                                "serve.admission_wait", r.t_enq,
                                t_pop - r.t_enq, r.trace_id,
                                {"batch": bid, "node": r.node_id})
                    else:
                        keep.append(r)
                self._held = keep
            deadline = t_first + max_wait
            # drain until the seed block is full or the first request's
            # wait budget is spent — a lone request ships at deadline,
            # a burst splits into back-to-back full batches
            while len(slots) < cap:
                remaining = deadline - time.perf_counter()
                if remaining <= 0:
                    break
                try:
                    if bcls is None:
                        req = self._pop_next(remaining)
                    else:
                        # class-pure: pull from the queue only (held
                        # was filtered above and now holds only other
                        # classes — re-popping it here would spin)
                        req = self._q.get(timeout=remaining)
                        self._note_popped(req)
                except queue.Empty:
                    break
                if self._shed_expired(req):
                    continue
                if bcls is not None and \
                        self._tenants[req.tenant] is not bcls:
                    self._held.append(req)
                    continue
                batch.append(req)
                slots.setdefault(req.node_id, len(slots))
                if traced:
                    t_pop = time.perf_counter()
                    pops.append((req, t_pop))
                    tracing.record("serve.admission_wait", req.t_enq,
                                   t_pop - req.t_enq, req.trace_id,
                                   {"batch": bid, "node": req.node_id})
            # the seed block keeps the engine's COMPILED width whatever
            # the fill cap — a fill-cap swap changes padding, never the
            # program shape
            seeds = np.full((self.engine.batch_cap,), -1, np.int32)
            for nid, s in slots.items():
                seeds[s] = nid
            variant = self._select_variant()
            if bcls is not None:
                # per-class quality-shed order: this class ignores
                # shed_grace ladder steps of the local shed level; the
                # fleet-planned floor still lower-bounds everyone
                top = len(self.engine.variants) - 1
                graced = max(0, min(self._shed_level, top)
                             - bcls.shed_grace)
                variant = max(graced, min(self._shed_floor, top))
            # the pipeline submit blocks at depth: device-side
            # backpressure propagates here, the queue absorbs it, and a
            # full queue sheds at admission — bounded everywhere
            try:
                pf = self._pipe.submit(self._execute, batch, slots,
                                       seeds, variant, bid)
            except RuntimeError:
                if self._closed:       # close() raced the coalescer
                    self._fail_batch(batch)
                    return
                raise
            if traced:
                t_sub = time.perf_counter()
                tracing.record("serve.batch_coalesce", t_first,
                               t_sub - t_first, bid,
                               {"requests": len(batch),
                                "fill": len(slots), "variant": variant})
                for req, t_pop in pops:
                    tracing.record("serve.coalesce_wait", t_pop,
                                   t_sub - t_pop, req.trace_id,
                                   {"batch": bid})
            # a batch the pipeline cancels while queued (close() drains
            # it) never reaches _execute — fail its futures, don't
            # strand them
            pf.add_done_callback(
                lambda f, b=batch:
                    self._fail_batch(b) if f.cancelled() else None)

    # -- shedding policy ----------------------------------------------------
    def _select_variant(self) -> int:
        """Quality-shed decision for the NEXT batch (coalescer thread
        only). Escalates one fanout step down the ladder when queue
        backlog crosses its threshold or the SLO error budget is
        burning unsustainably (``SloBudget.should_shed`` — the
        multi-window burn-rate signal that replaced the raw recent-p99
        trigger; it reacts to the RATE the budget is being spent, and
        counts failures/rejections the p99 samples never saw); recovers
        one step after ``calm_batches`` consecutive calm decisions —
        hysteresis, unchanged, so the variant mix doesn't flap (each
        flap costs nothing in compiles — every variant is pre-compiled
        — but a stable mix keeps the reported accuracy tradeoff
        meaningful). A planned fleet-wide floor (``set_shed_floor``,
        fed by ``fleet.HealthRouter.plan_quality``) lower-bounds the
        decision without disturbing the local hysteresis state."""
        top = len(self.engine.variants) - 1
        if top == 0:
            return 0
        cfg = self.config
        shed_at = max(1, int(cfg.queue_depth * cfg.shed_queue_frac))
        # held (class-deferred) requests are backlog too — they are
        # admitted work the coalescer has not dispatched yet
        pressed = self._q.qsize() + len(self._held) >= shed_at
        if not pressed and self.slo is not None:
            pressed = self.slo.should_shed()
        if pressed:
            self._shed_level = min(self._shed_level + 1, top)
            self._calm = 0
        elif self._shed_level:
            self._calm += 1
            if self._calm >= cfg.calm_batches:
                self._shed_level -= 1
                self._calm = 0
        return max(self._shed_level, min(self._shed_floor, top))

    # -- execution + scatter ------------------------------------------------
    def _fail_batch(self, batch, msg: str = "server closed before "
                                            "dispatch",
                    exc_type=ServerClosed):
        """Fail every not-yet-claimed future in ``batch`` loudly (with
        a TYPED error — ``ServerClosed`` subclasses RuntimeError, so a
        retrying RPC client can route elsewhere while legacy callers
        still catch it). The claim (``set_running_or_notify_cancel``)
        is exclusive, so this composes race-free with ``_execute`` and
        caller-side ``cancel()``; a future ``submit``'s close-race
        handler already failed counts as handled (``_fail_future``)."""
        failed = 0
        failed_reqs = []
        traced = tracing.enabled()
        now = time.perf_counter() if traced else 0.0
        for req in batch:
            if _fail_future(req.future, exc_type(msg)):
                failed += 1
                failed_reqs.append(req)
                if traced and req.trace_id is not None:
                    tracing.record("serve.request", req.t_enq,
                                   now - req.t_enq, req.trace_id,
                                   {"node": req.node_id,
                                    "error": exc_type.__name__})
        if failed:
            if self.slo is not None:
                for _ in range(failed):
                    self.slo.record(ok=False)
            with self._counts_lock:
                self._counts["failed"] += failed
                for req in failed_reqs:
                    st = self._tenant_states.get(req.tenant)
                    if st is not None:
                        st.counts["failed"] += 1
            if self._tenants is not None:
                for req in failed_reqs:
                    st = self._tenant_states.get(req.tenant)
                    if st is not None and st.budget is not None:
                        st.budget.record(ok=False)

    def _execute(self, batch, slots, seeds, variant, bid=None):
        # claim every request's future up front: a caller-side cancel()
        # that lands after this point loses the race cleanly (set_result
        # on a RUNNING future is legal; on a CANCELLED one it raises)
        batch = [r for r in batch
                 if r.future.set_running_or_notify_cancel()]
        if not batch:
            return
        t0 = time.perf_counter()
        try:
            faults.fire("serve.execute")
            logits = self.engine.run(seeds, variant)
            rows = np.asarray(jax.device_get(logits))
        except BaseException as e:
            # request-failure propagation: the batch's requests all see
            # the step's exception; the pipeline records the failure and
            # stays up for the next batch
            for req in batch:
                if not req.future.done():
                    req.future.set_exception(e)
            if self.slo is not None:
                for _ in batch:
                    self.slo.record(ok=False)
            with self._counts_lock:
                self._counts["failed"] += len(batch)
                for req in batch:
                    st = self._tenant_states.get(req.tenant)
                    if st is not None:
                        st.counts["failed"] += 1
            if self._tenants is not None:
                for req in batch:
                    st = self._tenant_states.get(req.tenant)
                    if st is not None and st.budget is not None:
                        st.budget.record(ok=False)
            if tracing.enabled():
                # error-stamped terminal spans: the failed requests'
                # traces complete with the outcome, so the tail
                # sampler's `error` policy keeps exactly these
                now = time.perf_counter()
                for req in batch:
                    if req.trace_id is not None:
                        tracing.record("serve.request", req.t_enq,
                                       now - req.t_enq, req.trace_id,
                                       {"batch": bid,
                                        "node": req.node_id,
                                        "error": type(e).__name__})
            raise
        done = time.perf_counter()
        traced = tracing.enabled() and bid is not None
        if traced:
            tracing.record("serve.dispatch", t0, done - t0, bid,
                           {"variant": variant, "fill": len(slots),
                            "requests": len(batch)})
        counters = (self.engine.last_counters
                    if self.engine.collect_metrics else None)
        self.stats.record_step(done - t0, counters)
        if self.hub is not None:
            # per-batch series for the telemetry hub's detectors and
            # the serving advisor (batch_cap from observed fill,
            # max_wait from observed latency); counters ride the hub's
            # own lazy fold — still no sync on the dispatch path
            self.hub.observe("serve_batch_fill", len(slots))
            self.hub.observe("serve_batch_ms", 1e3 * (done - t0))
            self.hub.observe("serve_shed_level", variant)
            if counters is not None:
                self.hub.observe_counters(counters)
        # stats and counts land BEFORE the futures resolve: a client
        # woken by result() may immediately snapshot(), and must see
        # its own batch counted
        for req in batch:
            lat = done - req.t_enq
            self.stats.record_request(lat)
            if self.slo is not None:
                self.slo.record(lat)
            if self._tenants is not None:
                st = self._tenant_states.get(req.tenant)
                if st is not None and st.budget is not None:
                    st.budget.record(lat)
        with self._counts_lock:
            self._counts["completed"] += len(batch)
            self._counts["batches"] += 1
            self._counts["coalesced"] += len(batch)
            self._counts["variant_batches"][variant] += 1
            if self._tenants is not None:
                for req in batch:
                    st = self._tenant_states.get(req.tenant)
                    if st is not None:
                        st.counts["completed"] += 1
                        st.hist.add(done - req.t_enq)
        for req in batch:
            req.future.set_result(rows[slots[req.node_id]])
        if traced:
            t_end = time.perf_counter()
            # scatter = stats filing + future resolution (the wake-up
            # cost requests pay after the device answer is back)
            tracing.record("serve.scatter", done, t_end - done, bid,
                           {"requests": len(batch)})
            for req in batch:
                tracing.record("serve.request", req.t_enq,
                               t_end - req.t_enq, req.trace_id,
                               {"batch": bid, "node": req.node_id,
                                "variant": variant})

    # -- observability ------------------------------------------------------
    def health(self) -> dict:
        """This replica's own health verdict — the same
        ``fleet.health_score`` formula the cross-process aggregator
        applies to every replica (SLO burn rate + shed level; a live
        server is never stale to itself), so a replica's self-report
        and the fleet view can only disagree about staleness, which
        only an outside observer can judge. Returns ``{"score",
        "components"}``."""
        from .fleet import health_score
        if getattr(self, "_broken", False):
            # a dead coalescer serves nothing: the self-report agrees
            # with what the fleet will conclude from staleness
            return {"score": 0.0, "components": {"broken": True}}
        burn = None
        if self.slo is not None:
            s = self.slo.burn_rate(self.slo.short_window_s)
            l = self.slo.burn_rate(self.slo.window_s)
            rates = [r for r in (s, l) if r is not None]
            burn = max(rates) if rates else None
        top = max(len(self.engine.variants) - 1, 1)
        score, components = health_score(
            burn=burn, shed_frac=self._shed_level / top)
        return {"score": score, "components": components}

    def snapshot(self) -> dict:
        """One JSONL-ready record (kind ``serving``): the underlying
        ``StepStats`` snapshot (per-request AND per-batch latency
        percentiles, device counters, recompiles, pipeline queue) plus
        the serving-layer facts — admission/shed counts, batch fill,
        per-variant batch mix, current shed level — and, when an SLO is
        configured, the ``SloBudget`` block (burn rates, remaining
        error budget; also emittable standalone as kind ``slo`` via
        ``server.slo.emit(sink)``)."""
        rec = self.stats.snapshot()
        if self.slo is not None:
            rec["slo"] = self.slo.snapshot()
        with self._counts_lock:
            c = dict(self._counts)
            c["variant_batches"] = list(c["variant_batches"])
        b = c.pop("batches")
        coalesced = c.pop("coalesced")
        rec["serving"] = {
            **c,
            "batches": b,
            "mean_batch_fill": coalesced / b if b else 0.0,
            "queue_depth": self._q.qsize(),
            "shed_level": self._shed_level,
            "fanout_variants": [list(v) for v in self.engine.variants],
            "health": self.health()["score"],
            "knobs": self.knobs(),
        }
        home = getattr(self.engine, "home", None)
        if home is not None:
            # sharded engine: per-replica partition ownership, the
            # fleet plane's routing/locality pivot (qt_top, the
            # locality router's ownership column)
            rec["serving"]["partition"] = {
                "home": int(home),
                "partitions": int(getattr(self.engine, "partitions", 1)),
            }
        return rec

    def emit(self, sink, kind: str = "serving") -> dict:
        """Append :meth:`snapshot` to a ``metrics.MetricsSink``."""
        return sink.emit(self.snapshot(), kind=kind)

    def tenant_snapshots(self) -> list:
        """One JSONL-ready record per registered tenant class (kind
        ``tenant``): the class declaration (priority, admission weight,
        shed grace), the admission/outcome counters, the derived
        ``shed`` total (rejected + displaced + deadline-expired — every
        request the policy turned away), the per-tenant latency
        histogram summary, and — when the class declares an SLO — its
        ``SloBudget`` block. Empty list when no registry was
        configured, so callers can emit unconditionally."""
        if self._tenants is None:
            return []
        recs = []
        with self._counts_lock:
            frozen = [(name, dict(st.counts), st.queued,
                       st.hist.n, st.hist.total, st.hist.max,
                       st.hist.quantile(0.5), st.hist.quantile(0.99))
                      for name, st in sorted(self._tenant_states.items())]
        for (name, c, queued, n, total, mx, p50, p99) in frozen:
            st = self._tenant_states[name]
            cls = st.cls
            rec = {
                "tenant": name,
                "priority": cls.priority,
                "admission_weight": cls.admission_weight,
                "shed_grace": cls.shed_grace,
                "queued": queued,
                "shed": (c["rejected"] + c["displaced"]
                         + c["deadline_expired"]),
                **c,
                "latency": {
                    "n": n,
                    "mean_ms": 1e3 * total / n if n else None,
                    "p50_ms": 1e3 * p50 if n else None,
                    "p99_ms": 1e3 * p99 if n else None,
                    "max_ms": 1e3 * mx if n else None,
                },
            }
            if st.budget is not None:
                rec["slo"] = st.budget.snapshot()
            recs.append(rec)
        return recs

    def emit_tenants(self, sink) -> list:
        """Append one per-tenant record per registered class to a
        ``metrics.MetricsSink`` as kind ``tenant`` — the per-tenant
        leg of the observability plane (TelemetryHub ingests these
        into ``tenant_*`` series; the fleet aggregator exports them as
        ``qt_tenant_*{tenant=...}``)."""
        recs = self.tenant_snapshots()
        for rec in recs:
            sink.emit(rec, kind="tenant")
        return recs

    def report(self) -> str:
        """Human-readable one-stop summary."""
        s = self.snapshot()
        sv = s["serving"]
        lines = [self.stats.report()]
        lines.append(
            f"serving: {sv['requests']} requests "
            f"({sv['rejected']} shed at admission, {sv['failed']} "
            f"failed), {sv['batches']} batches, mean fill "
            f"{sv['mean_batch_fill']:.1f}/{self.engine.batch_cap}, "
            f"variant mix {sv['variant_batches']}, shed level "
            f"{sv['shed_level']}")
        if "slo" in s:
            sl = s["slo"]
            short = sl["windows"]["short"]["burn_rate"]
            long_ = sl["windows"]["long"]["burn_rate"]
            rem = sl["budget_remaining"]
            fmt = lambda v: "n/a" if v is None else f"{v:.2f}"
            lines.append(
                f"slo: p99 target {sl['target_p99_ms']:.1f} ms at "
                f"{100.0 * sl['availability']:.1f}% — burn rate "
                f"{fmt(short)} (short) / {fmt(long_)} (long), "
                f"budget remaining "
                f"{'n/a' if rem is None else f'{100.0 * rem:.1f}%'}"
                f"{', SHEDDING' if sl['shedding'] else ''}")
        for t in self.tenant_snapshots():
            p99 = t["latency"]["p99_ms"]
            lines.append(
                f"tenant {t['tenant']}: {t['requests']} requests, "
                f"{t['completed']} completed, {t['shed']} shed "
                f"({t['rejected']} rejected, {t['displaced']} "
                f"displaced, {t['deadline_expired']} expired), p99 "
                f"{'n/a' if p99 is None else f'{p99:.1f} ms'}")
        return "\n".join(lines)
